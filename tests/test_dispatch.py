"""One-sync solve: sync-budget regression + dispatch instrumentation.

The counter-backed acceptance gate of the async-dispatch pipeline
(DESIGN.md section 12): every solve route -- adaptive self-solve, legacy
pack, external query (adaptive, legacy, and the chunked pipeline), and the
sharded per-chip engine -- must complete within
``runtime.dispatch.SYNC_BUDGET`` (= 2) host round trips on the reference's
20k fixture, where the pre-PR-5 engine blocked on three readbacks per
capacity class.  Also pins:

  * the ``fetch``/``stage`` counting semantics the budget test relies on,
  * byte-identity of the chunked (double-buffered) external-query pipeline
    against the single-shot path,
  * the executable-signature cache (reuse across same-signature launches),
  * the ``_finalize`` fallback bugfix: an uncertified row costs exactly one
    extra batched fetch, never a second sync storm.
"""

import numpy as np
import pytest

from cuda_knearests_tpu import KnnConfig, KnnProblem
from cuda_knearests_tpu.io import generate_blue_noise, generate_uniform
from cuda_knearests_tpu.runtime import dispatch


def _count(run):
    dispatch.reset_stats()
    out = run()
    return dispatch.stats(), out


# -- counting semantics -------------------------------------------------------

def test_fetch_batches_as_one_sync():
    import jax.numpy as jnp

    a = jnp.arange(128, dtype=jnp.float32)
    b = jnp.arange(64, dtype=jnp.int32)
    stats, (ha, hb) = _count(lambda: dispatch.fetch(a, b))
    assert stats.host_syncs == 1
    assert stats.d2h_bytes == a.nbytes + b.nbytes
    assert isinstance(ha, np.ndarray) and isinstance(hb, np.ndarray)
    np.testing.assert_array_equal(ha, np.arange(128, dtype=np.float32))


def test_fetch_host_only_is_free():
    stats, _ = _count(lambda: dispatch.fetch(np.zeros(8), [np.ones(3), None]))
    assert stats.host_syncs == 0 and stats.d2h_bytes == 0


def test_stage_counts_h2d_not_sync():
    import jax

    x = np.zeros((16, 3), np.float32)
    stats, dev = _count(lambda: dispatch.stage(x))
    assert isinstance(dev, jax.Array)
    assert stats.h2d_bytes == x.nbytes and stats.host_syncs == 0
    # re-staging an already-device array moves nothing
    stats, _ = _count(lambda: dispatch.stage(dev))
    assert stats.h2d_bytes == 0


def test_signature_census():
    a = np.zeros((4, 3), np.float32)
    b = np.zeros((4, 3), np.float32)
    assert dispatch.signature((a,), 8) == dispatch.signature((b,), 8)
    assert dispatch.signature((a,), 8) != dispatch.signature((a,), 9)
    assert dispatch.signature((a,), 8) != dispatch.signature(
        (a.astype(np.int32),), 8)


def test_executable_cache_reuse():
    cache = dispatch.ExecutableCache(maxsize=4)
    built = []

    def build():
        built.append(1)
        return "exe"

    key = dispatch.signature((np.zeros(3),), "s")
    assert cache.get_or_build(key, build) == "exe"
    assert cache.get_or_build(key, build) == "exe"
    assert len(built) == 1 and cache.hits == 1 and cache.misses == 1

    def boom():
        raise RuntimeError("no AOT here")

    assert cache.get_or_build(("other",), boom) is None
    assert not cache.enabled  # failed build disables, callers fall back
    assert cache.get_or_build(key, build) is None  # disabled: jitted path


def test_executable_cache_bounded_lru():
    """ISSUE 6 satellite: the cache is BOUNDED -- a long-lived daemon's
    cache evicts least-recently-used entries at the cap, counts evictions,
    and a hit refreshes recency."""
    cache = dispatch.ExecutableCache(maxsize=2)
    for name in ("a", "b"):
        cache.get_or_build((name,), lambda name=name: f"exe-{name}")
    assert cache.get_or_build(("a",), lambda: "rebuilt") == "exe-a"  # hit
    cache.get_or_build(("c",), lambda: "exe-c")  # evicts b (LRU), not a
    st = cache.stats_dict()
    assert st["exec_cache_evictions"] == 1 and st["exec_cache_size"] == 2
    assert st["exec_cache_cap"] == 2
    assert cache.get_or_build(("a",), lambda: "rebuilt") == "exe-a"
    built = []
    cache.get_or_build(("b",), lambda: built.append(1) or "exe-b2")
    assert built == [1]  # b was really evicted: rebuilt on next use
    cache.clear()
    st = cache.stats_dict()
    assert (st["exec_cache_hits"], st["exec_cache_misses"],
            st["exec_cache_evictions"], st["exec_cache_size"]) == (0, 0, 0, 0)


def test_exec_cache_cap_env_knob(monkeypatch):
    monkeypatch.setenv("KNTPU_EXEC_CACHE_CAP", "7")
    assert dispatch._env_cache_cap() == 7
    monkeypatch.setenv("KNTPU_EXEC_CACHE_CAP", "junk")
    assert dispatch._env_cache_cap() == dispatch.DEFAULT_EXEC_CACHE_ENTRIES
    monkeypatch.setenv("KNTPU_EXEC_CACHE_CAP", "-3")
    assert dispatch._env_cache_cap() == 1  # clamped, never unbounded
    monkeypatch.delenv("KNTPU_EXEC_CACHE_CAP")
    assert dispatch._env_cache_cap() == dispatch.DEFAULT_EXEC_CACHE_ENTRIES


# -- the sync-budget regression gate (ISSUE 5 acceptance) ---------------------

@pytest.fixture(scope="module")
def queries_2k():
    return generate_uniform(2_000, seed=99)


def test_budget_adaptive_solve(pts20k):
    p = KnnProblem.prepare(pts20k, KnnConfig(k=10))
    assert p.aplan is not None  # the adaptive route, not a stand-in
    stats, res = _count(p.solve)
    assert stats.host_syncs <= dispatch.SYNC_BUDGET
    assert np.asarray(res.certified).all()


def test_budget_legacy_pack_solve(pts20k):
    p = KnnProblem.prepare(pts20k, KnnConfig(k=10, adaptive=False))
    assert p.plan is not None
    stats, _ = _count(p.solve)
    assert stats.host_syncs <= dispatch.SYNC_BUDGET


def test_budget_external_query_adaptive(pts20k, queries_2k):
    p = KnnProblem.prepare(pts20k, KnnConfig(k=10))
    stats, (ids, d2) = _count(lambda: p.query(queries_2k))
    assert stats.host_syncs <= dispatch.SYNC_BUDGET
    assert ids.shape == (2_000, 10) and (np.diff(d2, axis=1) >= 0).all()


def test_budget_external_query_legacy_chunked(pts20k, queries_2k):
    p = KnnProblem.prepare(pts20k, KnnConfig(k=10, adaptive=False,
                                             query_chunk=256))
    stats, (ids, _) = _count(lambda: p.query(queries_2k))
    # 8 chunks, still <= 2 syncs: the pipeline batches all readbacks
    assert stats.host_syncs <= dispatch.SYNC_BUDGET
    assert ids.shape == (2_000, 10)


def test_budget_sharded_solve_and_query(pts20k, queries_2k):
    from cuda_knearests_tpu.parallel.sharded import ShardedKnnProblem

    sp = ShardedKnnProblem.prepare(pts20k, n_devices=8,
                                   config=KnnConfig(k=10))
    stats, (nbrs, _, cert) = _count(sp.solve)
    assert stats.host_syncs <= dispatch.SYNC_BUDGET
    assert cert.all() and nbrs.shape == (pts20k.shape[0], 10)
    stats, (ids, d2) = _count(lambda: sp.query(queries_2k))
    assert stats.host_syncs <= dispatch.SYNC_BUDGET
    assert ids.shape == (2_000, 10) and (np.diff(d2, axis=1) >= 0).all()


def test_fallback_is_one_extra_fetch_not_a_storm(uniform_10k):
    """The _finalize bugfix: with uncertified rows, the brute resolution
    rides ONE more batched fetch (2 round trips total), and the resolved
    result is exact."""
    p = KnnProblem.prepare(uniform_10k, KnnConfig(k=24, ring_radius=1))
    stats, res = _count(p.solve)
    assert stats.host_syncs <= dispatch.SYNC_BUDGET
    assert np.asarray(res.certified).all()  # fallback resolved every row
    # differential vs the no-starvation plan: identical neighbors
    ref = KnnProblem.prepare(uniform_10k, KnnConfig(k=24))
    ref.solve()
    np.testing.assert_array_equal(ref.get_knearests_original(),
                                  p.get_knearests_original())


# -- chunked pipeline: byte-identity + executable reuse -----------------------

def _query_both(points, queries, chunk, **cfg_kw):
    outs = {}
    for label, qc in (("single", None), ("chunked", chunk)):
        p = KnnProblem.prepare(points, KnnConfig(query_chunk=qc, **cfg_kw))
        outs[label] = p.query(queries)
    return outs


def test_chunked_matches_single_shot_brute(pts20k, queries_2k):
    """Default CPU legacy route (brute primary): chunking must not change a
    byte."""
    outs = _query_both(pts20k, queries_2k, chunk=300, k=10, adaptive=False)
    np.testing.assert_array_equal(outs["single"][0], outs["chunked"][0])
    np.testing.assert_array_equal(outs["single"][1], outs["chunked"][1])


def test_chunked_matches_single_shot_kernel(blue_8k, rng):
    """Interpret-mode kernel route: chunks share one executable signature
    (one shared q2cap) and stay byte-identical to single shot."""
    queries = rng.uniform(0.0, 1000.0, (700, 3)).astype(np.float32)
    outs = _query_both(blue_8k, queries, chunk=200, k=8, adaptive=False,
                       backend="pallas", interpret=True)
    np.testing.assert_array_equal(outs["single"][0], outs["chunked"][0])
    np.testing.assert_array_equal(outs["single"][1], outs["chunked"][1])


def test_chunked_kernel_reuses_executable(blue_8k, rng):
    """Across same-shape chunks the executable cache must hit (when the
    backend can AOT-lower at all; a disabled cache skips, not fails)."""
    queries = rng.uniform(0.0, 1000.0, (600, 3)).astype(np.float32)
    p = KnnProblem.prepare(blue_8k, KnnConfig(
        k=8, adaptive=False, backend="pallas", interpret=True,
        query_chunk=150))
    dispatch.EXEC_CACHE.clear()
    p.query(queries)
    if not dispatch.EXEC_CACHE.enabled:
        pytest.skip("backend cannot AOT-lower the query launch")
    st = dispatch.EXEC_CACHE.stats_dict()
    assert st["exec_cache_misses"] >= 1
    assert st["exec_cache_hits"] >= 1  # chunks 2..4 reuse chunk 1's compile
    # a repeat query re-traces nothing
    before = st["exec_cache_hits"]
    p.query(queries)
    assert dispatch.EXEC_CACHE.stats_dict()["exec_cache_hits"] > before


def test_query_chunk_resolution():
    cfg = KnnConfig(query_chunk=128)
    assert cfg.resolved_query_chunk() == 128
    assert KnnConfig().resolved_query_chunk() is None
    assert KnnConfig(query_chunk=0).resolved_query_chunk() is None
