"""Tier-1 gate for the adversarial fuzzing subsystem + the input front door
(ISSUE 4).

Layers, mirroring the subsystem:

* the unified front door (io.validate_or_raise) and its typed taxonomy:
  every refusal class, the ValueError/DeviceMemoryError compatibility
  bridge, and the 'invalid-input' failure-kind classification;
* degenerate sizes across ALL FOUR routes (n in {1, k-1, k}, k > n,
  all-duplicate input) -- the coverage test_properties.py only had for the
  single-chip core;
* the corpus replay policy: every banked repro in tests/corpus/*.npz must
  replay CLEAN on the fixed tree (each pins a campaign find);
* the seeded-fault self-test: KNTPU_FUZZ_FAULT in {drop-neighbor,
  perturb-d2, skip-route} must each yield a campaign failure with a
  minimized, banked repro -- proof the harness detects breakage;
* the campaign driver itself (manifest schema, waiver accounting, budget
  truncation) and its supervisor-isolated worker path.
"""

import glob
import os

import numpy as np
import pytest

from cuda_knearests_tpu.fuzz.campaign import (CaseFailure, WAIVERS,
                                              _route_failure, bank_case,
                                              load_banked, run_campaign,
                                              run_case)
from cuda_knearests_tpu.fuzz.compare import Mismatch, check_route_result
from cuda_knearests_tpu.fuzz.generators import (CaseSpec, draw_cases,
                                                generate_case, hazard_of,
                                                zoo_names)
from cuda_knearests_tpu.fuzz.minimize import ddmin_points
from cuda_knearests_tpu.fuzz.routes import (ROUTE_NAMES, parse_fault,
                                            run_route)
from cuda_knearests_tpu.io import validate_or_raise
from cuda_knearests_tpu.utils.memory import (DeviceMemoryError,
                                             DomainBoundsError,
                                             InputContractError,
                                             InvalidKError,
                                             InvalidShapeError,
                                             NonFiniteInputError,
                                             classify_fault_text, to_device)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "corpus")


# -- the input front door -----------------------------------------------------

def test_front_door_accepts_legal_input():
    pts = np.array([[0.0, 0.0, 0.0], [1000.0, 1000.0, 1000.0]], np.float32)
    out = validate_or_raise(pts, k=5)
    assert out.dtype == np.float32 and out.flags["C_CONTIGUOUS"]
    # n = 0 is legal (degraded mode: empty results downstream)
    assert validate_or_raise(np.empty((0, 3), np.float32)).shape == (0, 3)
    # k > n is legal degraded mode, validated only for positivity
    validate_or_raise(np.zeros((2, 3), np.float32), k=50)


@pytest.mark.parametrize("bad,exc", [
    (np.zeros((3, 2), np.float32), InvalidShapeError),
    (np.zeros((3,), np.float32), InvalidShapeError),
    ("not points", InvalidShapeError),
    (np.array([[1.0, 2.0, np.nan]]), NonFiniteInputError),
    (np.array([[1.0, 2.0, np.inf]]), NonFiniteInputError),
    (np.array([[-1.0, 2.0, 3.0]]), DomainBoundsError),
    (np.array([[1.0, 2.0, 1001.0]]), DomainBoundsError),
])
def test_front_door_rejects_typed(bad, exc):
    with pytest.raises(exc):
        validate_or_raise(bad)
    # compat: every refusal is still a ValueError (and an
    # InputContractError with the 'invalid-input' kind stamp)
    with pytest.raises(ValueError):
        validate_or_raise(bad)
    with pytest.raises(InputContractError) as ei:
        validate_or_raise(bad)
    assert ei.value.kind == "invalid-input"


@pytest.mark.parametrize("k", [0, -3, 2.5, True, "ten"])
def test_front_door_rejects_bad_k(k):
    with pytest.raises(InvalidKError):
        validate_or_raise(np.zeros((4, 3), np.float32), k=k)


def test_to_device_nonfinite_is_both_taxonomies():
    """to_device's refusal is typed into the input taxonomy AND still a
    DeviceMemoryError, so pre-existing catches keep working while the kind
    stamp says 'invalid-input' (the fix is the input, not the device)."""
    bad = np.array([1.0, np.nan], np.float32)
    with pytest.raises(NonFiniteInputError) as ei:
        to_device(bad)
    assert isinstance(ei.value, DeviceMemoryError)
    assert isinstance(ei.value, ValueError)
    assert ei.value.kind == "invalid-input"


def test_classify_fault_text_invalid_input():
    """The supervisor's stderr classifier recognizes the taxonomy by
    traceback spelling, so a worker that dies on illegal input records
    kind 'invalid-input' -- deterministic, never retried."""
    assert classify_fault_text(
        "NonFiniteInputError: points contain 2 NaN/inf") == "invalid-input"
    assert classify_fault_text(
        "InvalidKError: k must be >= 1") == "invalid-input"
    assert classify_fault_text(
        "violates the input contract") == "invalid-input"
    # transport still wins ties (retryability beats everything)
    assert classify_fault_text(
        "UNAVAILABLE: InvalidKError downstream") == "transport"
    # input-contract beats oom (a refusal may mention budgets)
    assert classify_fault_text(
        "InvalidConfigError: launch would exceed memory") == "invalid-input"


def test_route_surfaces_reject_illegal_queries():
    from cuda_knearests_tpu import KnnConfig, KnnProblem

    pts = (np.random.default_rng(0).random((40, 3)) * 1000).astype(np.float32)
    p = KnnProblem.prepare(pts, KnnConfig(k=4))
    p.solve()
    with pytest.raises(NonFiniteInputError):
        p.query(np.array([[np.nan, 1.0, 2.0]], np.float32))
    with pytest.raises(InvalidKError):
        p.query(pts[:2], k=9)  # beyond the prepared candidate dilation
    with pytest.raises(InvalidKError):
        p.query_radius(pts[:2], radius=10.0, max_neighbors=9)
    from cuda_knearests_tpu.parallel.sharded import ShardedKnnProblem

    sp = ShardedKnnProblem.prepare(pts, n_devices=2, config=KnnConfig(k=4))
    with pytest.raises(DomainBoundsError):
        sp.query(np.array([[2000.0, 0.0, 0.0]], np.float32))
    with pytest.raises(InvalidKError):
        sp.query(pts[:2], k=9)


# -- degenerate sizes across all four routes ----------------------------------

def _degenerate_cases():
    rng = np.random.default_rng(11)
    in_dom = lambda n: (rng.random((n, 3)) * 1000).astype(np.float32)  # noqa: E731
    return {
        "n1": (in_dom(1), 3),
        "n_eq_k_minus_1": (in_dom(3), 4),
        "n_eq_k": (in_dom(4), 4),
        "k_gt_n": (in_dom(4), 6),
        "all_duplicate": (np.full((12, 3), 321.5, np.float32), 5),
    }


@pytest.mark.parametrize("route", ROUTE_NAMES)
@pytest.mark.parametrize("case", sorted(_degenerate_cases()))
def test_degenerate_sizes_every_route(route, case):
    """n in {1, k-1, k}, k > n, and all-duplicate input must solve exactly
    (vs oracle, tie-aware) on EVERY route -- including the -1/inf padding
    contract when fewer than k neighbors exist."""
    points, k = _degenerate_cases()[case]
    assert _route_failure(points, k, route, n_devices=2) is None


def test_empty_input_every_route():
    """n = 0 is legal degraded mode on every route (the campaign's first
    find: the adaptive/legacy planners crashed; pinned by the banked
    corpus entries and fixed in api.KnnProblem/ops.gridhash)."""
    empty = np.empty((0, 3), np.float32)
    for route in ROUTE_NAMES:
        assert _route_failure(empty, 5, route, n_devices=2) is None, route


def test_k_gt_n_keeps_certificates_intact():
    """The documented degraded mode: k > n pads -1/inf and the result is
    still fully certified (nothing a bigger candidate set could add)."""
    from cuda_knearests_tpu import KnnConfig, KnnProblem

    pts = (np.random.default_rng(3).random((4, 3)) * 1000).astype(np.float32)
    p = KnnProblem.prepare(pts, KnnConfig(k=6))
    res = p.solve()
    nbrs = p.get_knearests_original()
    assert ((nbrs >= 0).sum(axis=1) == 3).all()  # n-1 real neighbors
    assert np.asarray(res.certified).all()


# -- corpus replay ------------------------------------------------------------

def _corpus_entries():
    # point-case repros only: mutation-stream (*-mutation.npz), FoF
    # (*-fof.npz), approx (*-approx.npz), fleet (*-fleet.npz) and pod
    # (*-pod.npz) repros have their own schemas and replay via their own
    # loaders (below / tests/test_cluster.py / test_mxu.py /
    # test_fleet.py / test_pod.py)
    return sorted(p for p in glob.glob(os.path.join(CORPUS, "*.npz"))
                  if not p.endswith(("-mutation.npz", "-fof.npz",
                                     "-approx.npz", "-fleet.npz",
                                     "-pod.npz")))


def _mutation_corpus_entries():
    return sorted(glob.glob(os.path.join(CORPUS, "*-mutation.npz")))


def _all_corpus_entries():
    # what fuzz.corpus_size() counts (and bench stamps as
    # fuzz_corpus_size): every banked repro of EVERY flavor
    return sorted(glob.glob(os.path.join(CORPUS, "*.npz")))


def test_corpus_is_nonempty():
    """The campaign's development finds are banked -- an empty corpus means
    the replay gate below is vacuous."""
    assert _corpus_entries(), f"no banked repros under {CORPUS}"


@pytest.mark.parametrize("path", _corpus_entries(),
                         ids=[os.path.basename(p) for p in _corpus_entries()])
def test_corpus_replays_clean(path):
    """Every banked minimal repro must stay fixed: the failure it recorded
    must NOT reproduce on the current tree (regression pin)."""
    b = load_banked(path)
    routes = ROUTE_NAMES if b["route"] == "all-routes" else (b["route"],)
    for route in routes:
        got = _route_failure(b["points"], b["k"], route, n_devices=2)
        assert got is None, (f"{os.path.basename(path)} regressed on "
                             f"{route}: {got} (originally: {b['reason']})")


def test_bank_roundtrip(tmp_path):
    spec = CaseSpec(generator="uniform", seed=1, n=5, k=2)
    pts = generate_case(spec)
    p = bank_case(str(tmp_path), spec, "query", "mismatch", "why", pts)
    b = load_banked(p)
    np.testing.assert_array_equal(b["points"], pts)
    assert (b["k"], b["route"], b["kind"]) == (2, "query", "mismatch")
    assert b["spec"] == spec and b["hazard"] == hazard_of("uniform")


# -- seeded-fault self-test ---------------------------------------------------

_FAULT_EXPECT = {
    "drop-neighbor": "mismatch",
    "perturb-d2": "mismatch",
    "skip-route": "missing-route",
}


@pytest.mark.parametrize("fault", sorted(_FAULT_EXPECT))
def test_seeded_fault_yields_minimized_banked_failure(fault, tmp_path,
                                                      monkeypatch):
    """The harness must detect its own seeded breakage: each fault kind
    yields a campaign failure whose repro is delta-minimized and banked
    (the acceptance criterion's self-test)."""
    monkeypatch.setenv("KNTPU_FUZZ_FAULT", fault)
    spec = CaseSpec(generator="uniform", seed=77, n=33, k=4)
    failures = run_case(spec, routes=("adaptive",), bank_dir=str(tmp_path),
                        minimize=True, max_probes=16)
    assert len(failures) == 1
    f = failures[0]
    assert f.kind == _FAULT_EXPECT[fault]
    assert f.banked and os.path.exists(f.banked)
    assert f.minimized_n is not None and f.minimized_n < f.original_n
    b = load_banked(f.banked)
    assert b["points"].shape[0] == f.minimized_n


def test_fault_only_hits_target_route(monkeypatch):
    monkeypatch.setenv("KNTPU_FUZZ_FAULT", "skip-route:legacy")
    assert parse_fault() == ("skip-route", "legacy")
    pts = (np.random.default_rng(5).random((20, 3)) * 1000).astype(np.float32)
    assert run_route("legacy", pts, 3) is None
    assert run_route("query", pts, 3) is not None
    monkeypatch.setenv("KNTPU_FUZZ_FAULT", "no-such-fault")
    with pytest.raises(ValueError, match="unknown KNTPU_FUZZ_FAULT"):
        run_route("query", pts, 3)


# -- comparison + minimizer units ---------------------------------------------

def test_compare_accepts_tie_flips():
    """Equal-distance neighbor sets must pass even when ids disagree with
    the oracle -- the whole point of tie-aware comparison."""
    pts = np.array([[0, 0, 0], [10, 0, 0], [0, 10, 0]], np.float32)
    q = np.array([[0, 0, 0]], np.float32)
    ref_d2 = np.array([[100.0, 100.0]], np.float32)  # oracle picked 1 then 2
    ids = np.array([[2, 1]], np.int32)               # route flipped the tie
    d2 = np.array([[100.0, 100.0]], np.float32)
    assert check_route_result(pts, q, ids, d2, ref_d2, 2) is None
    # but a genuinely different distance multiset fails
    bad = np.array([[100.0, 200.0]], np.float32)
    got = check_route_result(pts, q, np.array([[2, 1]], np.int32), bad,
                             ref_d2, 2)
    assert isinstance(got, Mismatch)


def test_ddmin_minimizes_to_culprit_subset():
    rng = np.random.default_rng(0)
    pts = rng.random((40, 3)).astype(np.float32)
    culprits = {7, 23}

    def fails(sub):
        # failure persists iff both culprit coordinates survive
        vals = {round(float(v[0]), 6) for v in sub}
        need = {round(float(pts[i, 0]), 6) for i in culprits}
        return need <= vals
    out, probes = ddmin_points(pts, fails, max_probes=200)
    assert out.shape[0] == 2 and probes <= 200
    assert fails(out)


# -- campaign driver ----------------------------------------------------------

def test_campaign_smoke_clean(tmp_path):
    manifest = run_campaign(n_cases=3, seed=0, routes=("adaptive", "query"),
                            bank_dir=str(tmp_path), isolation="none",
                            log=None)
    assert manifest["ok"] is True
    assert manifest["completed_cases"] == 3
    assert manifest["failures"] == [] and manifest["waived"] == []
    for key in ("seed", "routes", "isolation", "elapsed_s", "corpus_size",
                "truncated_after", "requested_cases", "waivers"):
        assert key in manifest


def test_campaign_budget_truncates_not_fails(tmp_path):
    manifest = run_campaign(n_cases=50, seed=0, routes=("query",),
                            bank_dir=str(tmp_path), isolation="none",
                            budget_s=0.0, log=None)
    assert manifest["ok"] is True
    assert manifest["completed_cases"] == 0
    assert manifest["truncated_after"] == 0


def test_campaign_failure_sets_not_ok(tmp_path, monkeypatch):
    monkeypatch.setenv("KNTPU_FUZZ_FAULT", "skip-route:query")
    manifest = run_campaign(n_cases=1, seed=0, routes=("query",),
                            bank_dir=str(tmp_path), isolation="none",
                            minimize=False, log=None)
    assert manifest["ok"] is False
    assert manifest["failures"][0]["kind"] == "missing-route"
    assert manifest["failures"][0]["banked"]


def test_waived_failure_keeps_campaign_ok(tmp_path, monkeypatch):
    monkeypatch.setenv("KNTPU_FUZZ_FAULT", "skip-route:query")
    monkeypatch.setitem(WAIVERS, ("*", "query"), "test: known-missing route")
    manifest = run_campaign(n_cases=1, seed=0, routes=("query",),
                            bank_dir=str(tmp_path), isolation="none",
                            minimize=False, log=None)
    assert manifest["ok"] is True
    assert manifest["failures"] == []
    assert manifest["waived"][0]["waived"] == "test: known-missing route"
    # a waived failure is EXPECTED to keep reproducing: banking it into the
    # replayed corpus would turn the waiver into a permanent tier-1 failure
    assert manifest["waived"][0]["banked"] is None
    assert list(tmp_path.iterdir()) == []


def test_faulted_run_never_banks_into_real_corpus(monkeypatch):
    """A KNTPU_FUZZ_FAULT self-test must not pollute tests/corpus with
    synthetic repros (they pin no engine bug and would replay as no-op
    tests forever): the default corpus dir diverts to a scratch dir."""
    from cuda_knearests_tpu.fuzz import CORPUS_DIR
    from cuda_knearests_tpu.fuzz.campaign import _safe_bank_dir

    monkeypatch.delenv("KNTPU_FUZZ_FAULT", raising=False)
    assert _safe_bank_dir(CORPUS_DIR) == CORPUS_DIR  # unfaulted: untouched
    monkeypatch.setenv("KNTPU_FUZZ_FAULT", "skip-route")
    diverted = _safe_bank_dir(CORPUS_DIR)
    assert diverted != CORPUS_DIR and os.path.isdir(diverted)
    # explicit scratch dirs (what the self-tests pass) are respected
    assert _safe_bank_dir("/tmp/some-scratch") == "/tmp/some-scratch"
    assert _safe_bank_dir(None) is None


def test_case_list_is_deterministic_and_covers_zoo():
    a = draw_cases(2 * len(zoo_names()), seed=9)
    b = draw_cases(2 * len(zoo_names()), seed=9)
    assert a == b
    assert {c.generator for c in a} == set(zoo_names())
    for c in a[:4]:
        pts = generate_case(c)
        np.testing.assert_array_equal(pts, generate_case(c))
        assert pts.shape == (c.n, 3) and pts.dtype == np.float32
        validate_or_raise(pts)  # every generated case is LEGAL input


def test_zoo_entries_are_tagged():
    assert len(zoo_names()) >= 10
    for name in zoo_names():
        assert hazard_of(name), name


# -- supervisor isolation -----------------------------------------------------

def test_supervised_case_runs_in_worker(tmp_path, monkeypatch):
    """The 'case' isolation path end-to-end: a fuzz_case job through a real
    supervisor worker child frames its (empty) failure list back."""
    from cuda_knearests_tpu.fuzz.campaign import _run_one
    from cuda_knearests_tpu.runtime.supervisor import Supervisor

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    spec = CaseSpec(generator="uniform", seed=2, n=8, k=2)
    out = _run_one(spec, ("query",), str(tmp_path), False, 1,
                   Supervisor(timeout_s=240))
    assert out == []


def test_supervised_worker_crash_banks_case(tmp_path, monkeypatch):
    """A worker SIGKILL (the containment case the supervisor exists for)
    costs one case: the parent banks the regenerable spec with the typed
    failure kind and the campaign continues."""
    from cuda_knearests_tpu.fuzz.campaign import _run_one
    from cuda_knearests_tpu.runtime.supervisor import Supervisor

    spec = CaseSpec(generator="uniform", seed=4, n=6, k=2)
    monkeypatch.setenv("KNTPU_FAULT", f"abort:{spec.case_id()}")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    out = _run_one(spec, ("query",), str(tmp_path), True, 1,
                   Supervisor(timeout_s=240))
    assert len(out) == 1 and out[0].kind == "crash"
    assert out[0].banked and os.path.exists(out[0].banked)
    b = load_banked(out[0].banked)
    assert b["points"].shape == (6, 3)


def test_corpus_size_stamp():
    from cuda_knearests_tpu.fuzz import corpus_size

    assert corpus_size() == len(_all_corpus_entries())
    assert corpus_size("/nonexistent/dir") == 0


def test_bench_rows_carry_fuzz_corpus_size():
    """Every bench artifact row is attributable to a fuzz-covered tree
    (the ISSUE 4 traceability satellite, like analysis_version in PR 3)."""
    import sys

    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    fields = bench._env_fields("cpu")
    assert fields.get("fuzz_corpus_size") == len(_all_corpus_entries())


# -- mutation-stream fuzzing (ISSUE 6 satellite: fuzz/mutation.py) ------------

@pytest.mark.parametrize("path", _mutation_corpus_entries() or ["<empty>"],
                         ids=[os.path.basename(p)
                              for p in _mutation_corpus_entries()] or ["none"])
def test_mutation_corpus_replays_clean(path):
    """Every banked mutation-stream repro must stay fixed on the current
    tree (regression pin, same policy as the point-case corpus)."""
    if path == "<empty>":
        pytest.skip("no banked mutation-stream repros (none found yet)")
    from cuda_knearests_tpu.fuzz.mutation import load_mutation_case, replay_ops

    b = load_mutation_case(path)
    got = replay_ops(b["spec"], b["ops"])
    assert got is None, (f"{os.path.basename(path)} regressed: {got} "
                         f"(originally: {b['reason']})")


def test_mutation_case_clean_and_deterministic():
    """A fixed-spec stream replays clean against the rebuild oracle, and
    its op list is regenerable (the corpus never ships arrays it can
    rebuild from four scalars)."""
    from cuda_knearests_tpu.fuzz.mutation import (MutationSpec, generate_ops,
                                                  run_mutation_case)

    spec = MutationSpec(seed=123, n0=80, n_ops=12, k=4)
    ops1, ops2 = generate_ops(spec), generate_ops(spec)
    assert [o["op"] for o in ops1] == [o["op"] for o in ops2]
    kinds = {o["op"] for o in ops1}
    assert "query" in kinds
    assert run_mutation_case(spec, bank_dir=None) is None


def test_mutation_seeded_fault_banks_minimized_repro(tmp_path, monkeypatch):
    """The self-test: a seeded overlay corruption must yield a detected,
    minimized, banked failure -- and the banked stream must round-trip."""
    from cuda_knearests_tpu.fuzz.mutation import (MutationSpec,
                                                  load_mutation_case,
                                                  replay_ops,
                                                  run_mutation_case)

    monkeypatch.setenv("KNTPU_MUT_FAULT", "drop-neighbor")
    spec = MutationSpec(seed=5, n0=60, n_ops=8, k=4)
    f = run_mutation_case(spec, bank_dir=str(tmp_path), max_probes=12)
    assert f is not None and f.kind == "mismatch"
    assert f.banked and os.path.exists(f.banked)
    assert f.minimized_ops is not None and f.minimized_ops < f.original_ops
    b = load_mutation_case(f.banked)
    assert b["spec"] == spec and len(b["ops"]) == f.minimized_ops
    monkeypatch.delenv("KNTPU_MUT_FAULT")
    # without the fault the banked repro replays CLEAN (regression-pin
    # semantics: the corpus pins fixes, not failures)
    assert replay_ops(b["spec"], b["ops"]) is None


def test_mutation_faulted_run_never_banks_into_real_corpus(monkeypatch):
    """Same diversion rule as the point campaign: synthetic KNTPU_MUT_FAULT
    repros must not pollute tests/corpus."""
    from cuda_knearests_tpu.fuzz import CORPUS_DIR
    from cuda_knearests_tpu.fuzz.mutation import _safe_bank_dir

    monkeypatch.setenv("KNTPU_MUT_FAULT", "perturb-d2")
    diverted = _safe_bank_dir(CORPUS_DIR)
    assert os.path.abspath(diverted) != os.path.abspath(CORPUS_DIR)
    assert _safe_bank_dir("/tmp/explicit") == "/tmp/explicit"
    monkeypatch.delenv("KNTPU_MUT_FAULT")
    assert _safe_bank_dir(CORPUS_DIR) == CORPUS_DIR


def test_mutation_campaign_manifest(tmp_path):
    from cuda_knearests_tpu.fuzz.mutation import run_mutation_campaign

    manifest = run_mutation_campaign(n_cases=2, seed=1,
                                     bank_dir=str(tmp_path), log=None)
    assert manifest["ok"] and manifest["completed_cases"] == 2
    assert manifest["flavor"] == "mutation-stream"
    for key in ("requested_cases", "truncated_after", "seed", "elapsed_s",
                "failures", "corpus_size"):
        assert key in manifest
