"""API-surface tests (C1): lifecycle, accessors, stats, degenerate inputs the
reference rejects outright (it exits for N < ~12K, knearests.cu:254-258)."""

import numpy as np
import pytest

from cuda_knearests_tpu import KnnConfig, KnnProblem, knn


def test_lifecycle_and_accessors(blue_8k):
    p = KnnProblem.prepare(blue_8k, KnnConfig(k=6))
    with pytest.raises(RuntimeError):
        p.get_knearests()  # solve() not called yet
    p.solve()
    assert p.get_points().shape == (len(blue_8k), 3)
    assert p.get_permutation().shape == (len(blue_8k),)
    assert p.get_knearests().shape == (len(blue_8k), 6)
    assert p.get_knearests_original().shape == (len(blue_8k), 6)
    assert p.get_dists_sq().shape == (len(blue_8k), 6)


def test_stats_shape(blue_8k, capsys):
    p = KnnProblem.prepare(blue_8k, KnnConfig(k=6))
    p.solve()
    s = p.print_stats()
    out = capsys.readouterr().out
    assert "points per cell" in out
    assert s["occupancy"]["num_points"] == len(blue_8k)
    assert abs(s["occupancy"]["avg_per_cell"] - 3.1) < 1.5
    assert s["certified_fraction"] == 1.0
    assert s["device_bytes"] > 0


def test_small_n_handled():
    """The reference exits for small N (knearests.cu:254-258 'does not support
    low number of input points'); this framework must not."""
    pts = np.random.default_rng(0).random((7, 3)).astype(np.float32) * 1000
    nbrs = knn(pts, k=10)
    assert nbrs.shape == (7, 10)
    assert (np.sort(nbrs[:, :6], axis=1) >= 0).all()
    assert (nbrs[:, 6:] == -1).all()  # only 6 possible neighbors exist


def test_single_point():
    nbrs = knn(np.array([[500.0, 500.0, 500.0]], np.float32), k=3)
    assert (nbrs == -1).all()


def test_identical_points():
    pts = np.full((20, 3), 321.0, np.float32)
    nbrs = knn(pts, k=4)
    for r in range(20):
        row = nbrs[r]
        assert r not in row.tolist()
        assert len(set(row.tolist())) == 4


def test_explicit_grid_dim(uniform_10k):
    p = KnnProblem.prepare(uniform_10k, KnnConfig(k=5), dim=9)
    assert p.grid.dim == 9
    p.solve()
    assert np.asarray(p.result.certified).all()


def test_k_one(uniform_10k):
    nbrs = knn(uniform_10k[:3000], k=1)
    assert nbrs.shape == (3000, 1)
    assert (nbrs >= 0).all()


def test_get_edges_directed_and_symmetric():
    import numpy as np

    from cuda_knearests_tpu import KnnConfig, KnnProblem
    from cuda_knearests_tpu.io import generate_uniform

    pts = generate_uniform(5000, seed=13)
    p = KnnProblem.prepare(pts, KnnConfig(k=4))
    p.solve()
    edges = p.get_edges()
    assert edges.shape == (5000 * 4, 2)
    assert (edges[:, 0] != edges[:, 1]).all()
    # row i's targets are exactly its neighbor list
    nbrs = p.get_knearests_original()
    assert set(edges[edges[:, 0] == 77][:, 1].tolist()) == set(nbrs[77].tolist())
    sym = p.get_edges(symmetric=True)
    # undirected closure: every edge has its reverse present
    fwd = set(map(tuple, sym.tolist()))
    assert all((b, a) in fwd for a, b in fwd)
    assert len(fwd) >= len(set(map(tuple, edges.tolist())))


def test_oracle_backend_matches_grid_engine(blue_8k):
    """backend='oracle' (the native kd-tree as a first-class engine) returns
    the same neighbors as the grid engine, in the same sorted-indexing
    result contract, with every row certified."""
    import numpy as np

    p_grid = KnnProblem.prepare(blue_8k, KnnConfig(k=10))
    p_grid.solve()
    p_orc = KnnProblem.prepare(blue_8k, KnnConfig(k=10, backend="oracle"))
    r = p_orc.solve()
    assert np.asarray(r.certified).all()
    np.testing.assert_array_equal(p_grid.get_knearests_original(),
                                  p_orc.get_knearests_original())
    np.testing.assert_allclose(p_grid.get_dists_sq(), p_orc.get_dists_sq(),
                               rtol=1e-6, atol=1e-3)
    # external queries ride the tree too, in ORIGINAL indexing
    q = blue_8k[:50] + 0.25
    gi, gd = p_grid.query(q, k=10)
    oi, od = p_orc.query(q, k=10)
    np.testing.assert_array_equal(np.sort(gi, 1), np.sort(oi, 1))
    # include-self variant
    p_inc = KnnProblem.prepare(blue_8k, KnnConfig(k=5, backend="oracle",
                                                  exclude_self=False))
    r5 = p_inc.solve()
    d0 = np.asarray(r5.dists_sq)[:, 0]
    assert (d0 == 0.0).all()  # self (dist 0) reported when not excluded
