"""Ring schedule tests (C3): shell structure, bound monotonicity and validity."""

import numpy as np

from cuda_knearests_tpu.ops.rings import (box_margin_bound_sq, dilated_box,
                                          ring_lower_bounds_sq, ring_schedule)


def test_schedule_counts():
    for nmax in (1, 2, 4, 16):
        s = ring_schedule(nmax)
        assert s.offsets.shape == ((2 * nmax - 1) ** 3, 3)
        # ring r has (2r+1)^3 - (2r-1)^3 cells; ring 0 is the center cell
        sizes = np.diff(s.ring_start)
        expect = [1] + [(2 * r + 1) ** 3 - (2 * r - 1) ** 3 for r in range(1, nmax)]
        np.testing.assert_array_equal(sizes, expect)
        # reference parity: nmax=16 -> 29,791 offsets (knearests.cu:288)
    assert ring_schedule(16).offsets.shape[0] == 29_791


def test_schedule_ring_membership_and_order():
    s = ring_schedule(5)
    chan = np.abs(s.offsets).max(axis=1)
    np.testing.assert_array_equal(chan, s.ring_of)
    assert (np.diff(s.ring_of) >= 0).all()  # ring-major order


def test_lower_bounds_valid_and_monotone():
    w = 37.5
    nmax = 6
    lb = ring_lower_bounds_sq(nmax, w)
    assert (np.diff(lb) >= 0).all()
    assert lb[0] == 0.0 and lb[1] == 0.0
    # validity: a point anywhere in the center cell vs any point in a ring-r
    # cell is at least sqrt(lb[r]) away
    rng = np.random.default_rng(0)
    s = ring_schedule(nmax)
    for _ in range(200):
        q = rng.random(3) * w  # in center cell [0,w)^3
        i = rng.integers(0, len(s.offsets))
        cell = s.offsets[i]
        p = (cell + rng.random(3)) * w
        assert ((q - p) ** 2).sum() >= lb[s.ring_of[i]] - 1e-4


def test_box_margin_bound():
    domain = 1000.0
    lo = np.array([100.0, 100.0, 100.0])
    hi = np.array([300.0, 300.0, 300.0])
    q = np.array([[150.0, 200.0, 250.0]])
    m2 = box_margin_bound_sq(q, lo, hi, domain)
    assert m2[0] == 50.0 ** 2  # closest face: x at 100
    # domain-clamped sides are unconstraining
    lo2 = np.array([0.0, 100.0, 100.0])
    q2 = np.array([[10.0, 200.0, 200.0]])
    m2b = box_margin_bound_sq(q2, lo2, hi, domain)
    assert m2b[0] == 100.0 ** 2  # x-low side ignored; y/z margins = 100
    # fully-open box -> infinite margin
    m2c = box_margin_bound_sq(q2, np.zeros(3), np.full(3, domain), domain)
    assert np.isinf(m2c[0])


def test_dilated_box_clamps():
    lo, hi = dilated_box((0, 1, 2), supercell=4, radius=2, dim=10)
    np.testing.assert_array_equal(lo, [0, 2, 6])
    np.testing.assert_array_equal(hi, [6, 10, 10])
