"""I/O layer tests (C11/C15): xyz format, normalization contract, generators."""

import numpy as np
import pytest

from cuda_knearests_tpu import DOMAIN_SIZE
from cuda_knearests_tpu.io import (bbox, generate_blue_noise, generate_uniform,
                                   load_xyz, normalize_points, save_xyz)


def test_xyz_roundtrip(tmp_path, rng):
    pts = rng.random((257, 3)).astype(np.float32) * 123.0
    path = str(tmp_path / "pts.xyz")
    save_xyz(path, pts)
    back = load_xyz(path)
    assert back.shape == (257, 3)
    np.testing.assert_allclose(back, pts, rtol=1e-6)


def test_xyz_header_mismatch(tmp_path):
    path = str(tmp_path / "bad.xyz")
    with open(path, "w") as f:
        f.write("5\n0 0 0\n1 1 1\n")
    with pytest.raises(ValueError):
        load_xyz(path)


def test_normalize_domain_contract(rng):
    pts = rng.random((5000, 3)).astype(np.float32) * [3.0, 70.0, 1.0] + [5, -9, 2]
    out = normalize_points(pts)
    assert out.min() >= 0.0 and out.max() <= DOMAIN_SIZE
    # longest side maps to ~domain, aspect preserved (test_knearests.cu:65-78);
    # compare raw point spans (bbox() pads, which would distort short axes)
    spans_in = pts.max(0) - pts.min(0)
    spans_out = out.max(0) - out.min(0)
    ratio = spans_out / spans_in
    np.testing.assert_allclose(ratio, ratio[np.argmax(spans_in)], rtol=1e-3)


def test_generators_shapes_and_domain():
    u = generate_uniform(3000, seed=1)
    b = generate_blue_noise(3000, seed=1)
    for pts in (u, b):
        assert pts.shape == (3000, 3) and pts.dtype == np.float32
        assert pts.min() >= 0.0 and pts.max() <= DOMAIN_SIZE


def _occupancy_var(pts, dim=18):
    """Variance of the points-per-cell histogram: the skew measure both
    generator-shape tests compare against uniform."""
    from cuda_knearests_tpu.ops.gridhash import cell_ids
    import jax.numpy as jnp

    cid = np.asarray(cell_ids(jnp.asarray(pts), dim))
    return np.bincount(cid, minlength=dim ** 3).var()


def test_blue_noise_is_more_even_than_uniform():
    """Blue noise should concentrate the occupancy histogram (smaller variance
    of points-per-cell than i.i.d. uniform)."""
    n = 20_000
    assert _occupancy_var(generate_blue_noise(n, seed=5)) \
        < 0.7 * _occupancy_var(generate_uniform(n, seed=5))


def test_generators_deterministic():
    a = generate_blue_noise(1000, seed=9)
    b = generate_blue_noise(1000, seed=9)
    np.testing.assert_array_equal(a, b)


def test_clustered_generator_contract():
    """generate_clustered: shape/domain/determinism plus the property the
    bench row depends on -- the occupancy histogram must be heavily skewed
    vs uniform (tight blobs over background), the opposite tail from blue
    noise."""
    from cuda_knearests_tpu.io import generate_clustered

    n = 20_000
    c = generate_clustered(n, seed=3)
    assert c.shape == (n, 3) and c.dtype == np.float32
    # <=: the f64 clip bound rounds back to exactly DOMAIN_SIZE in f32
    assert c.min() >= 0.0 and c.max() <= DOMAIN_SIZE
    np.testing.assert_array_equal(c, generate_clustered(n, seed=3))
    vc = _occupancy_var(c)
    vu = _occupancy_var(generate_uniform(n, seed=3))
    assert vc > 5.0 * vu, (vc, vu)


# -- request-stream front door (ISSUE 6 satellite: io.validate_request) -------

def test_validate_request_query_ok():
    from cuda_knearests_tpu.io import validate_request

    q = generate_uniform(5, seed=1)
    out = validate_request("query", q, k=3, k_max=10, max_batch=64)
    assert out.shape == (5, 3) and out.dtype == np.float32


def test_validate_request_typed_refusals():
    from cuda_knearests_tpu.io import validate_request
    from cuda_knearests_tpu.utils.memory import (InputContractError,
                                                 InvalidKError,
                                                 InvalidRequestError)

    q = generate_uniform(4, seed=2)
    with pytest.raises(InvalidRequestError, match="unknown request kind"):
        validate_request("solve", q)
    with pytest.raises(InvalidKError, match="serving k"):
        validate_request("query", q, k=20, k_max=10)
    with pytest.raises(InvalidRequestError, match="max_batch"):
        validate_request("query", generate_uniform(9, seed=3), max_batch=8)
    with pytest.raises(InputContractError):  # domain bounds via points path
        validate_request("insert", q - 500.0)
    # every refusal carries the 'invalid-input' kind the rc-5 path keys on
    try:
        validate_request("delete", np.array([3, 3]), n_current=10)
    except InputContractError as e:
        assert e.kind == "invalid-input"
    else:
        raise AssertionError("duplicate delete ids must refuse")


def test_validate_request_delete_contract():
    from cuda_knearests_tpu.io import validate_request
    from cuda_knearests_tpu.utils.memory import InvalidRequestError

    out = validate_request("delete", np.array([1, 4, 2]), n_current=10)
    assert out.tolist() == [1, 4, 2]
    with pytest.raises(InvalidRequestError, match="integer"):
        validate_request("delete", np.array([0.5]), n_current=10)
    with pytest.raises(InvalidRequestError, match="current cloud"):
        validate_request("delete", np.array([10]), n_current=10)
    with pytest.raises(InvalidRequestError, match="current cloud"):
        validate_request("delete", np.array([-1]), n_current=10)
