"""kntpu-trace (ISSUE 13): span tracer, metrics registry, flight
recorder, bench regression gate, and the serve-tier latency
decomposition.

The acceptance pins live here: the fleet bench rows stamp the
span-sourced queue/dispatch/device decomposition whose components sum to
within 5% of measured end-to-end latency on the 20k fixture; a
crash-injected supervised job's failure artifact carries the killed
worker's flight-recorder tail (>= 32 spans); ``scripts/bench_diff.py``
passes the committed baseline against itself and fails a seeded
synthetic regression.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from cuda_knearests_tpu.obs import metrics as obs_metrics
from cuda_knearests_tpu.obs import recorder as obs_recorder
from cuda_knearests_tpu.obs import spans as obs_spans

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- spans --------------------------------------------------------------------

def test_span_schema_nesting_and_validation():
    with obs_spans.capture() as events:
        with obs_spans.span("outer", a=1):
            with obs_spans.span("inner", trace_id="t-1"):
                pass
        obs_spans.event("marker", note="x")
    assert [e["name"] for e in events] == ["inner", "outer", "marker"]
    for e in events:
        assert obs_spans.validate_event(e) is None, e
    inner, outer, marker = events
    assert inner["depth"] == 1 and inner["parent"] == "outer"
    assert outer["depth"] == 0 and outer["attrs"] == {"a": 1}
    assert inner["trace_id"] == "t-1"
    assert marker["kind"] == "event" and marker["dur_ms"] == 0.0
    # wall anchoring: the inner span starts at/after the outer one
    assert inner["t0"] >= outer["t0"]


def test_disabled_fast_path_is_shared_singleton():
    assert not obs_spans.enabled()
    assert obs_spans.span("a") is obs_spans.span("b")
    # forced spans still time without any sink
    with obs_spans.span("forced", force=True) as sp:
        pass
    assert sp.t1 >= sp.t0 and sp.dur_ms >= 0.0


def test_span_records_exception_and_propagates():
    with obs_spans.capture() as events:
        with pytest.raises(ValueError):
            with obs_spans.span("dies"):
                raise ValueError("boom")
    assert events[0]["attrs"]["error"] == "ValueError"


def test_broken_sink_never_breaks_the_engine():
    def bad_sink(ev):
        raise RuntimeError("sink bug")

    obs_spans.add_sink(bad_sink)
    try:
        with obs_spans.span("survives"):
            pass
    finally:
        obs_spans.remove_sink(bad_sink)


def test_solve_trace_capture_nests_dispatch_children():
    """The instrumented seams: prepare/solve/query spans appear, and the
    dispatch fetch spans nest INSIDE the solve span tree."""
    from cuda_knearests_tpu import KnnConfig, KnnProblem
    from cuda_knearests_tpu.io import generate_uniform

    pts = generate_uniform(2000, seed=11)
    with obs_spans.capture() as events:
        p = KnnProblem.prepare(pts, KnnConfig(k=6))
        p.solve()
        p.query(generate_uniform(64, seed=12))
    names = {e["name"] for e in events}
    assert {"knn.prepare", "knn.solve", "knn.query",
            "dispatch.fetch"} <= names
    fetch_depths = [e["depth"] for e in events
                    if e["name"] == "dispatch.fetch"]
    assert fetch_depths and all(d >= 1 for d in fetch_depths)


# -- metrics ------------------------------------------------------------------

def test_histogram_percentiles_and_extrema():
    h = obs_metrics.Histogram("t")
    for v in range(1, 1001):          # 1..1000 ms uniform
        h.observe(float(v))
    snap = h.snapshot()
    assert snap["count"] == 1000 and snap["min"] == 1.0 \
        and snap["max"] == 1000.0
    assert abs(snap["sum"] - 500500.0) < 1e-3
    p50 = h.percentile(0.5)
    p99 = h.percentile(0.99)
    assert 400 <= p50 <= 600, p50          # geometric buckets: ~17% wide
    assert 900 <= p99 <= 1000.0, p99
    assert obs_metrics.Histogram("e").percentile(0.5) is None


def test_registry_snapshot_and_provider_error_isolation():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("c").inc(3)
    reg.gauge("g").set(2.5)
    reg.histogram("h").observe(1.0)
    reg.register_provider("ok", lambda: {"x": 1})
    reg.register_provider("bad",
                          lambda: (_ for _ in ()).throw(RuntimeError("p")))
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3 and snap["gauges"]["g"] == 2.5
    assert snap["histograms"]["h"]["count"] == 1
    assert snap["providers"]["ok"] == {"x": 1}
    assert "error" in snap["providers"]["bad"]


def test_unified_snapshot_schema_and_jsonl_emitter(tmp_path):
    snap = obs_metrics.metrics_snapshot()
    for key in ("v", "ts", "pid", "counters", "gauges", "histograms",
                "providers", "dispatch", "exec_cache"):
        assert key in snap, key
    assert snap["v"] == obs_metrics.SCHEMA
    assert "host_syncs" in snap["dispatch"]
    json.dumps(snap)                       # wire-serializable as-is

    path = tmp_path / "metrics.jsonl"
    em = obs_metrics.JsonlEmitter(str(path), period_s=0.05)
    em.start()
    import time

    time.sleep(0.18)
    em.stop()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) >= 2                 # periodic + final
    assert all(ln["v"] == obs_metrics.SCHEMA for ln in lines)


# -- flight recorder ----------------------------------------------------------

def test_recorder_ring_bound_spill_and_tail(tmp_path):
    rec = obs_recorder.FlightRecorder(capacity=16)
    spill = tmp_path / "flight.jsonl"
    rec.arm(tag="t", spill_path=str(spill))
    try:
        for i in range(50):
            with obs_spans.span(f"s{i}", force=True):
                pass
        rec.metric_delta()
    finally:
        rec.disarm()
    dump = rec.dump()
    assert len(dump["events"]) == 16       # ring stays bounded
    assert dump["dropped"] == dump["recorded"] - 16
    assert dump["events"][-1]["kind"] == "metrics"
    # the spill kept EVERYTHING (line-flushed: survives SIGKILL)
    tail = obs_recorder.read_spill_tail(str(spill), n=64)
    assert len(tail) == 52                 # arm marker + 50 spans + delta
    assert tail[0]["name"] == "recorder.arm"
    # torn final line (killed mid-write) is skipped, not fatal
    with open(spill, "a") as f:
        f.write('{"torn": ')
    assert len(obs_recorder.read_spill_tail(str(spill), n=8)) == 7


def test_supervised_crash_row_carries_flight_tail(tmp_path, monkeypatch):
    """ISSUE 13 acceptance: a crash-injected supervised job's failure
    record reconstructs the killed worker's last >= 32 spans.  The
    abort-after fault SIGKILLs the worker upon its 40th recorded event
    -- mid-work, exactly like a libtpu kill -- and the supervisor
    harvests the line-flushed spill."""
    from cuda_knearests_tpu.runtime import Supervisor

    monkeypatch.setenv("KNTPU_FAILURE_DIR", str(tmp_path))
    monkeypatch.setenv("KNTPU_FAULT", "abort-after:crashy:40")
    monkeypatch.setenv("BENCH_ROW_TIMEOUT_S", "120")
    row, failure = Supervisor().run_job(
        "crashy", {"job": "selftest", "spans": 64})
    assert row is None and failure is not None
    assert failure.kind == "crash" and failure.signal == 9
    spans = [e for e in failure.flight_tail if e.get("kind") == "span"]
    assert len(spans) >= 32, len(failure.flight_tail)
    assert all(e["job"] == "worker:crashy" for e in failure.flight_tail)
    # the artifact schema carries it (bench failure rows embed to_json())
    assert len(failure.to_json()["flight_tail"]) >= 32


def test_watchdog_stall_artifact_contains_flight_tail(tmp_path,
                                                      monkeypatch):
    """ISSUE 13 satellite: under KNTPU_FAULT=hang the worker's stall
    watchdog must leave a failure artifact containing BOTH the
    faulthandler all-thread dump and the flight-recorder tail -- the
    contents are asserted, not just the dump path."""
    import glob

    from cuda_knearests_tpu.runtime import Supervisor

    monkeypatch.setenv("KNTPU_FAILURE_DIR", str(tmp_path))
    monkeypatch.setenv("KNTPU_FAULT", "hang:hangy:120")
    monkeypatch.setenv("BENCH_STALL_TIMEOUT_S", "1")
    monkeypatch.setenv("BENCH_ROW_TIMEOUT_S", "60")
    row, failure = Supervisor().run_job(
        "hangy", {"job": "selftest", "spans": 4})
    assert row is None and failure.kind == "timeout"
    assert failure.rc == 3                 # the worker self-exited
    arts = glob.glob(str(tmp_path / "stall_*.tb"))
    assert arts, "no stall artifact written"
    content = open(arts[0]).read()
    assert "most recent call first" in content       # faulthandler frames
    assert "flight recorder tail" in content
    tail_json = content.split("=== flight recorder tail ===", 1)[1]
    dump = json.loads(tail_json.strip().splitlines()[0])
    assert dump["tag"] == "worker:hangy"
    assert any(e["name"] == "recorder.arm" for e in dump["events"])
    assert any(e["kind"] == "metrics" for e in dump["events"])


# -- export -------------------------------------------------------------------

def test_export_merges_processes_into_chrome_trace(tmp_path):
    from cuda_knearests_tpu.obs import export as obs_export

    def fake(pid, job, name, t0):
        return {"v": obs_spans.SCHEMA, "kind": "span", "name": name,
                "t0": t0, "dur_ms": 1.0, "depth": 0, "parent": "",
                "pid": pid, "job": job, "tid": "main",
                "trace_id": "r-1", "attrs": {"n": 1}}

    f1 = tmp_path / "trace_a_100.jsonl"
    f2 = tmp_path / "trace_b_200.jsonl"
    f1.write_text(json.dumps(fake(100, "worker:a", "s1", 10.0)) + "\n"
                  + "{torn\n")
    f2.write_text(json.dumps(fake(200, "worker:b", "s2", 9.0)) + "\n")
    summary = obs_export.export_dir(str(tmp_path),
                                    str(tmp_path / "merged.json"))
    assert summary["files"] == 2 and summary["events"] == 2
    chrome = json.load(open(tmp_path / "merged.json"))
    evs = chrome["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert {m["args"]["name"] for m in meta} == {"worker:a", "worker:b"}
    assert len(xs) == 2
    # time-sorted and rebased: the earlier event (t0=9.0) leads at ts 0
    assert xs[0]["name"] == "s2" and xs[0]["ts"] == 0.0
    assert xs[1]["ts"] == pytest.approx(1e6)
    assert xs[0]["args"]["trace_id"] == "r-1"


# -- serve decomposition (the 20k-fixture acceptance pin) --------------------

def test_serve_decomposition_components_sum_on_20k_fixture():
    """ISSUE 13 acceptance: per-request queue/dispatch/device components
    (span-sourced) sum to within 5% of the measured end-to-end latency
    on the 20k fixture."""
    from cuda_knearests_tpu import KnnConfig, KnnProblem
    from cuda_knearests_tpu.config import ServeConfig
    from cuda_knearests_tpu.io import get_dataset
    from cuda_knearests_tpu.serve.daemon import ServeDaemon

    points = get_dataset("pts20K.xyz")
    problem = KnnProblem.prepare(points, KnnConfig(k=8, adaptive=False))
    daemon = ServeDaemon(problem, ServeConfig(max_batch=64,
                                              max_delay_s=0.002))
    rng = np.random.default_rng(7)
    responses = []
    for i in range(12):
        qs = (rng.random((64, 3)) * 900.0 + 50.0).astype(np.float32)
        responses.extend(daemon.submit(req_id=i, kind="query",
                                       payload=qs,
                                       trace_id=f"req-{i}"))
    responses.extend(daemon.drain())
    ok = [r for r in responses if r.ok and r.ids is not None]
    assert len(ok) == 12
    total_e2e = 0.0
    total_sum = 0.0
    for r in ok:
        assert r.trace_id is not None
        assert r.queue_ms is not None and r.queue_ms >= 0.0
        assert r.dispatch_ms is not None and r.device_ms is not None
        e2e_ms = r.latency_s * 1e3
        comp = r.queue_ms + r.dispatch_ms + r.device_ms
        total_e2e += e2e_ms
        total_sum += comp
        # per-response: within 5% (plus a sub-ms scheduling floor)
        assert abs(comp - e2e_ms) <= max(0.05 * e2e_ms, 0.75), \
            (comp, e2e_ms)
    # the aggregate 5% criterion, no floor
    assert abs(total_sum - total_e2e) <= 0.05 * total_e2e, \
        (total_sum, total_e2e)
    # the daemon's bounded histograms saw every component
    deco = daemon.latency_decomposition()
    for name in ("total_ms", "queue_ms", "dispatch_ms", "device_ms"):
        assert deco[name]["p50"] is not None, deco
    # and the wire reply carries the timing block + trace id
    wire = ok[0].to_wire()
    assert wire["trace_id"] == ok[0].trace_id
    assert set(wire["timing"]) == {"queue_ms", "dispatch_ms",
                                   "device_ms"}


def _load_bench():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def test_fleet_mix_bench_row_stamps_decomposition(monkeypatch):
    """ISSUE 13 acceptance: the fleet_4tenant_mix bench row stamps the
    span-sourced p50/p99 latency decomposition, fleet-wide and per
    tenant."""
    monkeypatch.setenv("BENCH_FLEET_N", "600")
    monkeypatch.setenv("BENCH_FLEET_REQUESTS", "8")
    bench = _load_bench()
    row = bench.serve_scenario("fleet_4tenant_mix")
    deco = row["latency_decomposition"]
    for name in ("queue_ms", "dispatch_ms", "device_ms"):
        assert deco[name]["p50"] is not None, deco
        assert deco[name]["p99"] is not None, deco
    for tenant, pt in row["per_tenant"].items():
        if pt["served_rows"] and not pt["sidecar"]:
            assert pt["decomposition"]["device_ms"]["p50"] is not None, \
                (tenant, pt)


def test_fleet_failover_row_stamps_decomposition():
    """ISSUE 13 acceptance: the failover drill's row decomposes its
    wire-level request latency (child-framed op/device timings)."""
    from cuda_knearests_tpu.serve.fleet.replica import failover_drill

    drill = failover_drill(n=400, k=6, ops=12, seed=3)
    assert drill["failover_ok"], drill
    deco = drill["latency_decomposition"]
    for name in ("total_ms", "queue_ms", "dispatch_ms", "device_ms"):
        assert name in deco, deco
    assert deco["device_ms"]["p50"] is not None, deco


def test_serve_scenario_filter_env(monkeypatch):
    bench = _load_bench()
    monkeypatch.setenv("BENCH_SERVE_SCENARIOS", "fleet_failover")
    assert bench._serve_scenario_names() == ["fleet_failover"]
    monkeypatch.setenv("BENCH_SERVE_SCENARIOS", "nope")
    with pytest.raises(ValueError, match="unknown BENCH_SERVE_SCENARIOS"):
        bench._serve_scenario_names()
    monkeypatch.delenv("BENCH_SERVE_SCENARIOS")
    assert bench._serve_scenario_names() == list(bench._SERVE_SCENARIOS)


# -- bench regression gate ----------------------------------------------------

def _load_bench_diff():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "scripts", "bench_diff.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_diff_passes_committed_baseline_and_fails_seeded():
    """ISSUE 13 acceptance: rc 0 on the committed baseline vs itself,
    rc != 0 on a seeded synthetic regression."""
    bd = _load_bench_diff()
    baseline_files = [os.path.join(REPO, "bench_runs",
                                   "r5_cpu_all_rows.json"),
                      os.path.join(REPO, "BENCH_r05.json")]
    rc_same = bd.main(["--baseline", baseline_files[0],
                       "--baseline", baseline_files[1],
                       "--current", baseline_files[0]])
    assert rc_same == 0
    rc_selftest = bd.main(["--self-test",
                           "--baseline", baseline_files[0],
                           "--baseline", baseline_files[1]])
    assert rc_selftest == 0   # the self-test VERIFIES the seeded trip

    baseline = bd.load_rows(baseline_files)
    assert len(baseline) >= 7
    seeded = bd.seed_regression(baseline)
    verdicts, rc = bd.diff(baseline, seeded, dict(bd.KIND_TOLERANCE))
    assert rc != 0
    assert any(v["verdict"] == "regressed" for v in verdicts)


def test_bench_diff_verdict_taxonomy(tmp_path):
    bd = _load_bench_diff()
    base = {"config": "row A", "value": 100.0, "recall": 1.0,
            "steady_ok": True}
    # within tolerance: ok;  errored row gates;  missing is informational
    cur_ok = dict(base, value=90.0)
    v = bd.compare_row("row A", base, cur_ok, {"engine": 0.2})
    assert v["verdict"] == "ok"
    v = bd.compare_row("row A", base, dict(base, error="boom"),
                       {"engine": 0.2})
    assert v["verdict"] == "errored"
    v = bd.compare_row("row A", base, dict(base, steady_ok=False),
                       {"engine": 0.2})
    assert v["verdict"] == "regressed"
    v = bd.compare_row("row A", base, dict(base, recall=0.9),
                       {"engine": 0.2})
    assert v["verdict"] == "regressed"
    verdicts, rc = bd.diff({"row A": base}, {}, {"engine": 0.2})
    assert verdicts[0]["verdict"] == "missing" and rc == 0
    _, rc = bd.diff({"row A": base}, {}, {"engine": 0.2},
                    require_all=True)
    assert rc != 0


# -- the obs smoke itself -----------------------------------------------------

def test_obs_smoke_main_passes(tmp_path):
    from cuda_knearests_tpu.obs.__main__ import main as obs_main

    rc = obs_main(["--out-dir", str(tmp_path), "--n", "3000"])
    assert rc == 0
    chrome = json.load(open(tmp_path / "trace_merged.json"))
    assert chrome["traceEvents"]
    snap = json.loads((tmp_path / "metrics.jsonl").read_text()
                      .splitlines()[-1])
    assert snap["v"] == obs_metrics.SCHEMA


# -- metrics wire command -----------------------------------------------------

def test_metrics_wire_command_over_stdio():
    """The serve wire's `metrics` op returns one unified snapshot."""
    import subprocess

    req = (json.dumps({"id": 1, "op": "query",
                       "data": [[50.0, 50.0, 50.0]], "k": 4,
                       "trace_id": "wire-1"}) + "\n"
           + json.dumps({"id": 2, "op": "metrics"}) + "\n")
    r = subprocess.run(
        [sys.executable, "-m", "cuda_knearests_tpu.serve",
         "--points", "uniform:1500", "--k", "6", "--max-batch", "32",
         "--max-delay-ms", "2"],
        input=req, capture_output=True, text=True, timeout=180,
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr[-2000:]
    replies = [json.loads(ln) for ln in r.stdout.splitlines() if ln]
    by_id = {rep.get("id"): rep for rep in replies}
    q = by_id[1]
    assert q["ok"] and q["trace_id"] == "wire-1"
    assert set(q["timing"]) == {"queue_ms", "dispatch_ms", "device_ms"}
    m = by_id[2]
    assert m["ok"] and m["metrics"]["v"] == obs_metrics.SCHEMA
    assert "host_syncs" in m["metrics"]["dispatch"]
    assert "serve" in m["metrics"]
    assert "latency_decomposition" in m["metrics"]["serve"]


def test_snapshot_surfaces_tuned_plan_store_counters(tmp_path):
    """ISSUE 17 satellite: the unified snapshot carries the tuned-plan
    store counters (tune_store_*) next to the ExecutableCache compile
    stats, so one scrape answers both "did autotuning hit the persisted
    plans" and "what did compilation cost"."""
    from cuda_knearests_tpu.tune import store as tstore

    snap = obs_metrics.metrics_snapshot()
    assert "tuned_plans" in snap
    for key in ("exec_cache_hits", "exec_cache_misses",
                "exec_cache_compiled", "exec_cache_compile_s"):
        assert key in snap["exec_cache"], key
    prev = tstore.get_default_store()
    try:
        tstore.set_default_store(tstore.TunedPlanStore(
            path=str(tmp_path / "plans.json")))
        snap2 = obs_metrics.metrics_snapshot()
        for key in ("tune_store_hits", "tune_store_misses",
                    "tune_store_stores", "tune_store_cap"):
            assert key in snap2["tuned_plans"], key
        json.dumps(snap2)  # still one JSON-serializable document
    finally:
        tstore.set_default_store(prev)
