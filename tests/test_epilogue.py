"""Scatter vs gather epilogue: byte-identity across every consumer.

The scatter epilogue (config.epilogue, DESIGN.md section 2c) changes WHERE
results are laid out -- the kernel emits row-major rows at scalar-prefetched
offsets and classes place them through prepare-time forward maps -- but must
never change a single output byte.  These differentials pin ids, squared
distances, certified flags, and the in-program uncertified count equal
between the two modes on:

  * the interpret-mode Pallas kernel path (the TPU stand-in), adaptive and
    legacy single-pack both,
  * the compiled CPU path (dense/streamed class routes -- no kernel, the
    scatter placement alone),
  * a clustered fixture whose plan DROPS empty supercells,
  * external queries (both the adaptive class schedule and the legacy
    ops/query.py pipeline),
  * the sharded multi-chip engine on the emulated 8-device mesh.
"""

import dataclasses

import numpy as np
import pytest

from cuda_knearests_tpu import KnnConfig, KnnProblem
from cuda_knearests_tpu.config import resolve_epilogue
from cuda_knearests_tpu.io import (generate_blue_noise, generate_clustered,
                                   generate_uniform)


def _triple(res):
    return (np.asarray(res.neighbors), np.asarray(res.dists_sq),
            np.asarray(res.certified),
            int(np.asarray(res.uncert_count))
            if res.uncert_count is not None else None)


def _solve_both(points, **cfg_kw):
    outs = {}
    for epi in ("gather", "scatter"):
        p = KnnProblem.prepare(points, KnnConfig(epilogue=epi, **cfg_kw))
        outs[epi] = _triple(p.solve())
    return outs


def _assert_identical(outs):
    g, s = outs["gather"], outs["scatter"]
    np.testing.assert_array_equal(g[0], s[0])
    np.testing.assert_array_equal(g[1], s[1])
    np.testing.assert_array_equal(g[2], s[2])
    assert g[3] == s[3]


def test_resolve_epilogue_policy():
    assert resolve_epilogue("auto", on_kernel_platform=True) == "scatter"
    assert resolve_epilogue("auto", on_kernel_platform=False) == "gather"
    assert resolve_epilogue("gather", True) == "gather"
    assert resolve_epilogue("scatter", False) == "scatter"
    with pytest.raises(ValueError, match="unknown epilogue"):
        resolve_epilogue("scattr", True)


@pytest.mark.parametrize("fixture_name", ["uniform_10k", "blue_8k"])
def test_scatter_matches_gather_interpret_pallas(fixture_name, request):
    """Adaptive interpret-mode kernel path: the scalar-prefetch row-major
    kernel vs the raw-layout kernel + transpose + row gather."""
    points = request.getfixturevalue(fixture_name)
    _assert_identical(_solve_both(points, k=10, backend="pallas",
                                  interpret=True))


def test_scatter_matches_gather_compiled_cpu(pts20k):
    """Compiled (non-interpret) CPU path: dense class routes, scatter
    placement only -- the 'compiled CPU' half of the differential."""
    _assert_identical(_solve_both(pts20k, k=10))


def test_scatter_matches_gather_empty_supercells():
    """Clustered data leaves most supercells EMPTY (dropped from every
    class): the forward maps must still cover exactly the stored points and
    the sink rows must never surface."""
    points = generate_clustered(9_000, seed=31)
    _assert_identical(_solve_both(points, k=10, backend="pallas",
                                  interpret=True))
    _assert_identical(_solve_both(points, k=10))  # compiled CPU routes


def test_scatter_matches_gather_legacy_single_pack():
    """adaptive=False pins the legacy PallasPack path
    (pallas_solve._solve_packed's own scatter branch)."""
    points = generate_uniform(7_000, seed=13)
    _assert_identical(_solve_both(points, k=8, backend="pallas",
                                  interpret=True, adaptive=False))


def test_scatter_matches_gather_blocked_kernel():
    """kernel='blocked' has no row-major body: scatter mode must route it
    through the gather-layout launch + XLA transpose, byte-identically."""
    points = generate_blue_noise(7_000, seed=23)
    _assert_identical(_solve_both(points, k=10, backend="pallas",
                                  interpret=True, kernel="blocked"))


def test_scatter_matches_gather_external_queries(blue_8k, rng):
    """External queries through the adaptive class schedule and through the
    legacy ops/query.py pipeline, both epilogues."""
    queries = rng.uniform(0.0, 1000.0, (700, 3)).astype(np.float32)
    for extra in ({}, {"adaptive": False}):
        outs = {}
        for epi in ("gather", "scatter"):
            p = KnnProblem.prepare(blue_8k, KnnConfig(
                k=8, backend="pallas", interpret=True, epilogue=epi, **extra))
            outs[epi] = p.query(queries)
        np.testing.assert_array_equal(outs["gather"][0], outs["scatter"][0])
        np.testing.assert_array_equal(outs["gather"][1], outs["scatter"][1])


@pytest.mark.parametrize("backend,interpret", [("auto", True),
                                               ("xla", False)])
def test_scatter_matches_gather_sharded(backend, interpret):
    """The sharded engine: per-chip scatter placement through the
    halo-extended forward maps (backend='xla' pins the streamed route, so
    the non-kernel scatter placement is covered too)."""
    from cuda_knearests_tpu.parallel.sharded import ShardedKnnProblem

    points = generate_uniform(12_000, seed=8)
    outs = {}
    for epi in ("gather", "scatter"):
        p = ShardedKnnProblem.prepare(points, n_devices=8, config=KnnConfig(
            k=8, backend=backend, interpret=interpret, epilogue=epi))
        outs[epi] = p.solve()
    for i in range(3):
        np.testing.assert_array_equal(outs["gather"][i], outs["scatter"][i])


def test_unaligned_qcap_refused():
    """An unaligned qcap must raise BEFORE the grid is built -- pick_qsub
    128-rounds internally, so qcap=100 would silently produce an EMPTY grid
    (n_q = 100 // 128 == 0) with uninitialized outputs (ADVICE r5)."""
    import jax.numpy as jnp

    from cuda_knearests_tpu.ops.pallas_solve import (_pallas_topk,
                                                     _pallas_topk_rows)

    qcap, ccap, k = 100, 128, 4
    q = jnp.zeros((1, 1, qcap), jnp.float32)
    c = jnp.zeros((1, 1, ccap), jnp.float32)
    qi = jnp.zeros((1, 1, qcap), jnp.int32)
    ci = jnp.zeros((1, 1, ccap), jnp.int32)
    with pytest.raises(ValueError, match="multiple of 128"):
        _pallas_topk(q, q, q, c, c, c, qi, ci, qcap, ccap, k,
                     exclude_self=False, interpret=True)
    with pytest.raises(ValueError, match="multiple of 128"):
        _pallas_topk_rows(q, q, q, c, c, c, qi, ci, qcap, ccap, k,
                          exclude_self=False, interpret=True)


def test_scatter_refuses_planless_forward_map(uniform_10k):
    """A plan without forward maps (e.g. deserialized from a pre-scatter
    build) must fail loudly in scatter mode, not produce init-value rows."""
    p = KnnProblem.prepare(uniform_10k, KnnConfig(
        k=8, backend="pallas", interpret=True, epilogue="scatter"))
    stripped = dataclasses.replace(
        p.aplan,
        classes=tuple(dataclasses.replace(cp, tgt=None)
                      for cp in p.aplan.classes))
    p.aplan = stripped
    with pytest.raises(ValueError, match="predates the scatter epilogue"):
        p.solve()
