"""Lint fixture: bare timing on a serve/runtime path (bare-timing)."""
import time
from time import perf_counter


def measure_batch(run):
    t0 = time.perf_counter()        # finding: bare perf_counter timing
    run()
    elapsed = time.time() - t0      # finding: bare time.time timing
    t1 = perf_counter()             # finding: bare imported perf_counter
    waived = time.perf_counter()    # kntpu-ok: bare-timing -- fixture: demonstrates the waiver form
    legal = time.monotonic()        # injected-clock default: not a finding
    return elapsed, t1, waived, legal
