"""Fixture: torn-state hazard -- guarded attr written without its lock."""
import threading


class Counter:
    def __init__(self):
        self.lock = threading.Lock()
        self.total = 0  # pre-publication write: never flagged

    def add(self, n):
        with self.lock:
            self.total += n

    def reset(self):
        self.total = 0

    def reset_waived(self):
        self.total = 0  # kntpu-ok: unguarded-shared-mutable -- teardown path, single-threaded by contract
