"""Lint fixture: bare-valueerror must fire on untyped raises (never run)."""


def validate(points):
    if points.ndim != 2:
        raise ValueError("points must be (n, 3)")  # line 6: untyped raise
    if points.shape[0] == 0:
        raise ValueError  # line 8: bare-class re-raise form
    return points


def other(code):
    # unrelated exception types stay out of scope
    raise RuntimeError(f"not an input problem: {code}")
