"""Lint fixture: broad-except must fire without the marker (never run)."""


def swallow(fn):
    try:
        return fn()
    except Exception:  # line 7: no taxonomy marker, no re-raise
        return None
