"""Lint fixture: wide-dtype must fire on unmarked f64/i64 (never run)."""
import numpy as np


def widen(x):
    acc = np.asarray(x, np.float64)  # line 6: unmarked f64 widening
    idx = np.arange(8, dtype=np.int64)  # line 7: unmarked i64 widening
    return acc, idx
