"""Lint fixture: host-sync-loop must fire in the host loop (never run)."""
import jax
import numpy as np


def drain(chunks, out):
    for i, c in enumerate(chunks):
        out[i] = np.asarray(jax.device_get(c))  # line 8: device_get per iter
        c.block_until_ready()  # line 9: sync per iteration
        host = np.asarray(c)  # line 10: implicit sync on a device array
    return out
