"""Lint fixture: every hazard below carries its waiver -- zero findings.

Exercises the waiver syntax of each rule (and the broad-except re-raise
exemption), so a marker regression breaks this corpus, not production.
"""
import jax
import jax.numpy as jnp
import numpy as np


def audited(x, chunks, out, tables):
    acc = np.asarray(x, np.float64)  # kntpu-ok: wide-dtype -- fixture: intentional host precision
    for i, c in enumerate(chunks):
        out[i] = np.asarray(jax.device_get(c))  # kntpu-ok: host-sync-loop -- fixture: bounded readback
    staged = []
    for t in tables:
        staged.append(jnp.asarray(t))  # kntpu-ok: jnp-in-loop -- fixture: bounded prepare staging
    try:
        return acc, staged
    except Exception:  # noqa: BLE001 -- fixture: rationale present
        return None, None


def rewrap(fn):
    try:
        return fn()
    except Exception as e:  # broad but re-raises: the taxonomy-wrap pattern
        raise RuntimeError(f"wrapped: {e}") from e


def invariant(state):
    if state is None:
        raise ValueError("internal invariant, not input validation")  # kntpu-ok: bare-valueerror -- fixture: reasoned non-input raise
