"""Lint fixture: tracer-leak must fire inside the jitted body (never run)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def leaky(x):
    return np.sum(x)  # line 11: np.* on a tracer


@functools.partial(jax.jit, static_argnames=("k",))
def leaky_cast(x, k):
    return float(jnp.max(x)) + k  # line 16: float() forces a traced value


def host_side_is_fine(x):
    return np.sum(x)  # not jitted: silent
