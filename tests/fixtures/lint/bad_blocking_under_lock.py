"""Fixture: blocking calls while holding a lock stall every contender."""
import threading
import time

_lock = threading.Lock()


def tick(sock):
    with _lock:
        time.sleep(0.5)
        data = sock.recv(1024)
    return data


def tock(proc):
    with _lock:
        proc.communicate()  # kntpu-ok: blocking-under-lock -- child exited already: bounded drain
