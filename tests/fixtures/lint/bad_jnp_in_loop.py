"""Lint fixture: jnp-in-loop must fire in the host loop (never run)."""
import jax.numpy as jnp


def rebuild(tables):
    staged = []
    for t in tables:
        staged.append(jnp.asarray(t))  # line 8: device alloc per iteration
    return staged
