"""Fixture: ABBA deadlock shape -- opposite lock nesting in one file."""
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()


def forward():
    with a_lock:
        with b_lock:
            pass


def backward():
    with b_lock:
        with a_lock:
            pass
