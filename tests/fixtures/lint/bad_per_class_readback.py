"""Lint fixture: the retired per-class readback pattern (never run).

This is the exact shape the engine's query paths carried before the one-sync
solve (PR 5): one launch per capacity class, three blocking host readbacks
per class inside the loop.  The host-sync-loop rule must keep firing on it so
the pattern can never quietly return without a reasoned waiver.
"""
import jax
import numpy as np


def assemble(classes, launch, out_i, out_d, cert):
    for sel_sorted, cp in classes:
        r_i, r_d, r_c = launch(cp)
        out_i[sel_sorted] = np.asarray(jax.device_get(r_i))  # line 15
        out_d[sel_sorted] = np.asarray(jax.device_get(r_d))  # line 16
        cert[sel_sorted] = np.asarray(jax.device_get(r_c))   # line 17
    return out_i, out_d, cert
