"""Serving-fleet acceptance: multi-tenant isolation, cross-tenant executable
sharing, SLO/admission/fairness laws, replication + failover (DESIGN.md
section 17).

The ISSUE 11 gates pinned here:

  * two tenants of equal executable signature share the ExecutableCache --
    the second tenant's warmup takes ZERO new compiles, and LRU eviction
    pressure from one tenant never corrupts another tenant's answers
    (extends the ISSUE 8 eviction test);
  * the wire contract's tenant field refuses typed: unknown-tenant,
    over-quota, and tenant-mismatched k all surface as
    InvalidRequestError subclasses that classify_fault_text stamps
    'invalid-input';
  * token-bucket admission and deficit-round-robin scheduling enforce the
    fairness law (a flooding tenant cannot starve the rest), with the
    accounting stamped per dispatch;
  * replication commits through the delta log and failover (in-process
    AND process-level with a real SIGKILL) loses zero committed
    mutations, with post-failover answers byte-identical to the rebuild
    oracle on the mutated cloud;
  * tiny/degenerate tenants land on the CPU sidecar and promote to dense
    placements when they grow past the threshold;
  * every banked ``tests/corpus/*-fleet.npz`` repro replays clean, and
    each ``KNTPU_FLEET_FAULT`` corruption provably yields a detected
    failure that never pollutes the real corpus.
"""

import glob
import os
import time

import numpy as np
import pytest

from cuda_knearests_tpu import KnnConfig, KnnProblem
from cuda_knearests_tpu.config import (SLO_CLASSES, ServeFleetConfig,
                                       SloClass)
from cuda_knearests_tpu.fuzz.compare import check_route_result
from cuda_knearests_tpu.io import generate_uniform, validate_request
from cuda_knearests_tpu.runtime import dispatch
from cuda_knearests_tpu.serve.daemon import Response
from cuda_knearests_tpu.serve.fleet import (CpuSidecar, DrrScheduler,
                                            FleetDaemon, Replica,
                                            ReplicationLog, Tenant,
                                            TenantSpec, TokenBucket,
                                            failover_drill, jain_index)
from cuda_knearests_tpu.utils.memory import (InvalidConfigError,
                                             InvalidKError, OverQuotaError,
                                             TransportError,
                                             UnknownTenantError,
                                             classify_fault_text)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "corpus")

# Small fleets keep tier-1 fast; the threshold sits between the tiny and
# dense sizes so both placements are always exercised.
CFG = ServeFleetConfig(min_bucket=8, max_batch=64, compact_threshold=64,
                      warmup=True, sidecar_threshold=192, drr_quantum=16)


def _mk_points(n, seed):
    return generate_uniform(n, seed=seed)


def _responses_for(responses, req_id):
    return [r for r in responses if r.req_id == req_id]


def _query_through(fleet, req_id, tenant, queries, k=None):
    """Submit one query and flush everything; returns its one response."""
    out = fleet.submit(req_id, tenant, "query", queries, k=k)
    out += fleet.drain()
    mine = _responses_for(out, req_id)
    assert len(mine) == 1, [r.error for r in out if not r.ok]
    return mine[0]


# -- config: SLO classes + fleet tunables -------------------------------------

def test_slo_class_table():
    assert set(SLO_CLASSES) == {"latency", "throughput"}
    lat, thr = SLO_CLASSES["latency"], SLO_CLASSES["throughput"]
    assert lat.max_delay_s < thr.max_delay_s       # latency flushes fast
    assert lat.max_batch <= thr.max_batch          # throughput rides deep
    assert lat.p99_budget_ms < thr.p99_budget_ms


def test_serve_config_for_clamps_to_fleet_ladder():
    fleet = ServeFleetConfig(min_bucket=8, max_batch=32)
    sc = fleet.serve_config_for(SLO_CLASSES["throughput"])
    assert sc.max_batch == 32        # class depth clamps to the ladder cap
    assert sc.min_bucket == 8
    sc_lat = fleet.serve_config_for(SloClass("x", 0.001, 16, 100.0))
    assert sc_lat.max_batch == 16


def test_tenant_spec_validation_typed():
    with pytest.raises(InvalidConfigError):
        TenantSpec(name="t", slo="goldplated")
    with pytest.raises(InvalidConfigError):
        TenantSpec(name="t", ship_mode="osmosis")
    with pytest.raises(InvalidConfigError):
        TenantSpec(name="t", k=0)
    spec = TenantSpec(name="t", k=4, slo="latency")
    assert TenantSpec.from_json(spec.to_json()) == spec


def test_fleet_config_validation():
    with pytest.raises(ValueError):
        ServeFleetConfig(min_bucket=0)
    with pytest.raises(ValueError):
        ServeFleetConfig(min_bucket=16, max_batch=8)
    with pytest.raises(ValueError):
        ServeFleetConfig(drr_quantum=0)
    with pytest.raises(ValueError):
        ServeFleetConfig(quota_qps=0.0)
    with pytest.raises(ValueError):
        ServeFleetConfig(sidecar_threshold=-1)


# -- admission: token bucket + DRR fairness -----------------------------------

def test_token_bucket_refill_and_refusal():
    tb = TokenBucket(rate=10.0, burst=20.0, now=0.0)
    assert tb.try_take(20, now=0.0)          # the whole burst
    assert not tb.try_take(1, now=0.0)       # empty -> refuse, not queue
    assert tb.refusals == 1
    assert tb.try_take(5, now=0.5)           # 0.5s * 10/s = 5 tokens back
    assert not tb.try_take(1, now=0.5)
    # refill caps at burst, never beyond
    assert tb.try_take(20, now=1e9)
    assert not tb.try_take(21, now=1e9 + 2.0)


def test_token_bucket_unmetered():
    tb = TokenBucket(rate=None, burst=8.0, now=0.0)
    assert all(tb.try_take(10 ** 6, now=0.0) for _ in range(3))
    assert tb.refusals == 0


class _B:
    """Minimal batch stand-in: DRR reads only .total."""

    def __init__(self, total):
        self.total = total


def test_drr_no_starvation_under_flood():
    """The DRR law: a flooding tenant cannot starve a light one -- the
    light tenant's whole backlog dispatches while the hog is still paying
    for its deep batches, and every batch eventually dispatches."""
    from collections import deque

    drr = DrrScheduler(quantum=16)
    drr.register("hog")
    drr.register("light")
    ready = {"hog": deque(_B(64) for _ in range(6)),
             "light": deque(_B(8) for _ in range(2))}
    order = drr.select(ready)
    assert not ready["hog"] and not ready["light"]     # full drain
    tenants = [name for name, _batch, _disp in order]
    # the light tenant's 8-row batches are affordable within one quantum;
    # the hog's 64-row batches need four -- light finishes first
    assert tenants[:2] == ["light", "light"]
    assert tenants.count("hog") == 6
    assert drr.served_rows == {"hog": 384, "light": 16}
    # fairness accounting is stamped on every dispatch
    assert len(drr.dispatches) == 8
    for d in drr.dispatches:
        assert d.rows > 0 and d.deficit_after >= 0
    # an emptied queue resets its deficit (no banked credit while idle)
    assert drr.deficit["light"] == 0.0 and drr.deficit["hog"] == 0.0


def test_drr_rows_served_within_fairness_bound():
    """While both tenants stay backlogged, served rows differ by at most
    one quantum plus one max batch (the classic DRR bound)."""
    from collections import deque

    drr = DrrScheduler(quantum=16)
    drr.register("a")
    drr.register("b")
    ready = {"a": deque(_B(32) for _ in range(8)),
             "b": deque(_B(32) for _ in range(8))}
    drr.select(ready)
    a = b = 0
    for d in list(drr.dispatches)[:-1]:   # both backlogged until the last
        if d.tenant == "a":
            a += d.rows
        else:
            b += d.rows
        assert abs(a - b) <= 16 + 32, (a, b)


def test_slo_percentiles_are_query_only():
    """A mutation-only tenant has NO latency samples: its percentiles must
    come back None (mutation acks are near-instant and would dilute the
    p99 the slo_ok gate checks -- regression test)."""
    from cuda_knearests_tpu.serve.fleet import TenantLoad, run_fleet_session

    dispatch.EXEC_CACHE.clear()
    fleet = FleetDaemon(
        [(TenantSpec(name="w", k=4, slo="latency"), _mk_points(400, 70))],
        ServeFleetConfig(min_bucket=8, max_batch=64, warmup=False,
                         sidecar_threshold=192, drr_quantum=16))
    summary = run_fleet_session(fleet, [TenantLoad(
        tenant="w", rate=500.0, requests=4, mutation_ratio=1.0, seed=5)])
    pt = summary["per_tenant"]["w"]
    assert pt["offered_rows"] == 0 and pt["p99_ms"] is None
    assert not pt["slo_ok"]
    assert summary["slo_ok_all"]          # no offered queries -> excluded


def test_jain_index():
    assert jain_index([1.0, 1.0, 1.0, 1.0]) == 1.0
    assert jain_index([1.0, 0.0, 0.0, 0.0]) == 0.25   # one tenant took all
    assert jain_index([]) is None
    assert jain_index([0.0, 0.0]) is None
    assert jain_index([1.0, None, 1.0]) == 1.0        # absent != starved


# -- the wire contract's tenant field (typed refusals) ------------------------

def test_validate_request_unknown_tenant_typed():
    q = np.full((2, 3), 500.0, np.float32)
    with pytest.raises(UnknownTenantError) as ei:
        validate_request("query", q, tenant="ghost", tenants=("a", "b"))
    assert ei.value.kind == "invalid-input"
    assert classify_fault_text(
        f"{type(ei.value).__name__}: {ei.value}") == "invalid-input"


def test_validate_request_over_quota_typed():
    q = np.full((2, 3), 500.0, np.float32)
    with pytest.raises(OverQuotaError) as ei:
        validate_request("query", q, tenant="a", tenants=("a",),
                         quota_ok=False)
    assert ei.value.kind == "invalid-input"
    assert classify_fault_text(
        f"{type(ei.value).__name__}: {ei.value}") == "invalid-input"
    # quota verdicts never mask the tenant check
    with pytest.raises(UnknownTenantError):
        validate_request("query", q, tenant="ghost", tenants=("a",),
                         quota_ok=False)


def test_validate_request_tenant_k_mismatch_names_tenant():
    q = np.full((1, 3), 500.0, np.float32)
    with pytest.raises(InvalidKError) as ei:
        validate_request("query", q, k=32, k_max=8, tenant="acme",
                         tenants=("acme",))
    assert "acme" in str(ei.value)
    assert ei.value.kind == "invalid-input"


@pytest.fixture(scope="module")
def two_tenant_fleet():
    """Two dense tenants of EQUAL executable signature (same n, k, SLO)
    plus one sidecar tenant -- the fleet most tests drive."""
    dispatch.EXEC_CACHE.clear()
    builds = [
        (TenantSpec(name="a", k=8, slo="latency"), _mk_points(1200, 0)),
        (TenantSpec(name="b", k=8, slo="latency"), _mk_points(1200, 1)),
        (TenantSpec(name="tiny", k=8, slo="latency"), _mk_points(24, 2)),
    ]
    return FleetDaemon(builds, CFG)


def test_frontdoor_unknown_tenant_refused(two_tenant_fleet):
    [r] = two_tenant_fleet.submit(900, "ghost", "query",
                                  np.full((1, 3), 5.0, np.float32))
    assert not r.ok and r.failure_kind == "invalid-input"
    assert "unknown tenant" in r.error
    assert r.tenant == "ghost"


def test_frontdoor_over_quota_refused():
    dispatch.EXEC_CACHE.clear()
    fleet = FleetDaemon(
        [(TenantSpec(name="metered", k=4, slo="latency", quota_qps=1.0,
                     quota_burst=4.0), _mk_points(400, 3))], CFG)
    q8 = np.full((8, 3), 500.0, np.float32)
    [r] = fleet.submit(1, "metered", "query", q8)   # 8 rows > burst 4
    assert not r.ok and r.failure_kind == "invalid-input"
    assert "over quota" in r.error
    assert fleet.refused["metered"] == 1
    # within-burst traffic still admits
    r2 = _query_through(fleet, 2, "metered",
                        np.full((2, 3), 500.0, np.float32))
    assert r2.ok, r2.error


def test_frontdoor_k_mismatch_refused(two_tenant_fleet):
    [r] = two_tenant_fleet.submit(901, "a", "query",
                                  np.full((1, 3), 5.0, np.float32), k=64)
    assert not r.ok and r.failure_kind == "invalid-input"
    assert "serving k" in r.error


def test_oversized_query_refused_at_tenant_ladder_depth():
    """A query larger than the TENANT's SLO-clamped max_batch must refuse
    typed at admission -- not crash the front door when the tenant's
    batcher meets a batch its own ladder cannot bucket (regression: the
    front door used to validate against the fleet-global cap)."""
    dispatch.EXEC_CACHE.clear()
    fleet = FleetDaemon(
        [(TenantSpec(name="lat", k=4, slo="latency"), _mk_points(400, 60))],
        ServeFleetConfig(min_bucket=8, max_batch=256, warmup=False,
                         sidecar_threshold=192, drr_quantum=16))
    assert fleet._max_batch(fleet.tenants["lat"]) == 64  # class-clamped
    [r] = fleet.submit(1, "lat", "query",
                       np.full((100, 3), 500.0, np.float32))
    assert not r.ok and r.failure_kind == "invalid-input"
    # the daemon survives and keeps serving
    r2 = _query_through(fleet, 2, "lat", _mk_points(3, 61))
    assert r2.ok, r2.error


def test_drr_drains_deep_batch_behind_cheap_head():
    """A large batch queued BEHIND a cheap head must still drain (the
    rotation guard budgets on the biggest batch anywhere in the queues,
    not just current heads -- regression test)."""
    from collections import deque

    drr = DrrScheduler(quantum=1)
    drr.register("t")
    ready = {"t": deque([_B(1), _B(256)])}
    order = drr.select(ready)          # must not raise the invariant guard
    assert [b.total for _n, b, _d in order] == [1, 256]
    assert drr.served_rows["t"] == 257


def test_barrier_flushed_queries_ride_fleet_accounting():
    """Queries pending at a mutation barrier must execute through the
    fleet's own accounting (batch_log / served_rows), not vanish into the
    daemon's internal barrier flush (regression test)."""
    dispatch.EXEC_CACHE.clear()
    fleet = FleetDaemon(
        [(TenantSpec(name="m", k=4, slo="throughput"),
          _mk_points(400, 62))],
        ServeFleetConfig(min_bucket=8, max_batch=64, warmup=False,
                         sidecar_threshold=192, drr_quantum=16))
    out = fleet.submit(1, "m", "query", _mk_points(3, 63))   # stays pending
    assert out == []
    out = fleet.submit(2, "m", "insert",
                       np.full((2, 3), 400.0, np.float32))   # barrier
    assert all(r.ok for r in out), [r.error for r in out if not r.ok]
    assert {r.req_id for r in out} == {1, 2}
    assert any(b["reason"] == "barrier" for b in fleet.batch_log)
    assert fleet.served_rows["m"] == 3


# -- cross-tenant ExecutableCache sharing (the zero-recompile fleet law) ------

def test_second_equal_signature_tenant_warms_free():
    """Two tenants on the same ladder bucket set with equal problem
    signatures: the second tenant's warmup takes ZERO new compiles -- the
    whole point of coalescing the fleet onto one capacity ladder."""
    cache = dispatch.EXEC_CACHE
    cache.clear()
    t_a = Tenant(TenantSpec(name="a", k=8, slo="latency"),
                 _mk_points(1200, 10), CFG, time.monotonic)
    assert not t_a.is_sidecar
    misses_after_first = cache.misses
    assert misses_after_first > 0          # tenant a minted the buckets
    t_b = Tenant(TenantSpec(name="b", k=8, slo="latency"),
                 _mk_points(1200, 11), CFG, time.monotonic)
    assert not t_b.is_sidecar
    assert cache.misses == misses_after_first, \
        "second equal-signature tenant recompiled during warmup"
    assert cache.hits > 0


def test_fleet_steady_queries_zero_recompiles(two_tenant_fleet):
    """After fleet warmup, on-ladder queries across every dense tenant hit
    only cached executables."""
    misses0 = dispatch.EXEC_CACHE.misses
    for i, name in enumerate(("a", "b", "a", "b")):
        r = _query_through(two_tenant_fleet, 100 + i, name,
                           _mk_points(5, 40 + i))
        assert r.ok, r.error
    assert dispatch.EXEC_CACHE.misses == misses0


def test_eviction_pressure_never_corrupts_other_tenant():
    """Extends the ISSUE 8 eviction test across tenants: tenant A thrashes
    a tiny cache through differently-bucketed batches; tenant B's answers
    must re-mint executables and stay byte-identical to its own rebuild
    oracle -- eviction costs recompiles, never correctness or isolation."""
    cache = dispatch.EXEC_CACHE
    cache.clear()
    pts_a, pts_b = _mk_points(800, 20), _mk_points(800, 21)
    fleet = FleetDaemon(
        [(TenantSpec(name="a", k=8, slo="latency"), pts_a),
         (TenantSpec(name="b", k=8, slo="latency"), pts_b)],
        ServeFleetConfig(min_bucket=8, max_batch=64, warmup=False,
                         sidecar_threshold=192, drr_quantum=16))
    probe = _mk_points(6, 22)
    before = np.asarray(_query_through(fleet, 1, "b", probe).ids)
    old_cap = cache.maxsize
    try:
        cache.maxsize = 2                   # thrashing is now guaranteed
        for i, m in enumerate((1, 9, 17, 33)):
            r = _query_through(fleet, 10 + i, "a",
                               np.full((m, 3), 500.0, np.float32))
            assert r.ok, r.error
        assert cache.evictions > 0
        rb = _query_through(fleet, 50, "b", probe)
        assert rb.ok, rb.error
        oracle = KnnProblem.prepare(pts_b, KnnConfig(k=8, adaptive=False))
        ref_i, ref_d = oracle.query(probe, 8)
        np.testing.assert_array_equal(np.asarray(rb.ids),
                                      np.asarray(ref_i))
        np.testing.assert_array_equal(np.asarray(rb.d2),
                                      np.asarray(ref_d, np.float32))
        np.testing.assert_array_equal(np.asarray(rb.ids), before)
    finally:
        cache.maxsize = old_cap
        cache.clear()


# -- tenant isolation (answers come from the RIGHT cloud) ---------------------

def test_tenant_answers_its_own_cloud(two_tenant_fleet):
    """The same probe through tenants a and b must answer against each
    tenant's own points -- byte-identical to per-tenant rebuild oracles
    (dense path), different from each other (different clouds)."""
    probe = _mk_points(4, 30)
    for name in ("a", "b"):
        r = _query_through(two_tenant_fleet, 200 + ord(name), name, probe)
        assert r.ok and r.tenant == name
        oracle = KnnProblem.prepare(
            two_tenant_fleet.tenants[name].mutated_points(),
            KnnConfig(k=8, adaptive=False))
        ref_i, ref_d = oracle.query(probe, 8)
        np.testing.assert_array_equal(np.asarray(r.ids),
                                      np.asarray(ref_i))
        np.testing.assert_array_equal(np.asarray(r.d2),
                                      np.asarray(ref_d, np.float32))


# -- the CPU sidecar tier -----------------------------------------------------

def test_tiny_tenant_lands_on_sidecar(two_tenant_fleet):
    t = two_tenant_fleet.tenants["tiny"]
    assert t.is_sidecar and t.n_points == 24
    probe = _mk_points(3, 31)
    r = _query_through(two_tenant_fleet, 300, "tiny", probe)
    assert r.ok and r.tenant == "tiny"
    # exact under the tie-aware contract (host-numpy bits, not XLA bits)
    oracle = KnnProblem.prepare(t.mutated_points(),
                                KnnConfig(k=8, adaptive=False))
    _ref_i, ref_d = oracle.query(probe, 8)
    bad = check_route_result(t.mutated_points(), probe,
                             np.asarray(r.ids), np.asarray(r.d2),
                             np.asarray(ref_d), 8)
    assert bad is None, bad.render()


def test_degenerate_tenant_pads_like_dense():
    """n < k is a sidecar placement by definition; rows pad -1/inf beyond
    the available neighbors (the front door's degraded-mode contract)."""
    side = CpuSidecar(_mk_points(3, 32), k=8)
    ids, d2 = side.query(_mk_points(2, 33), 8)
    assert ids.shape == (2, 8) and d2.shape == (2, 8)
    assert (ids[:, 3:] == -1).all() and np.isinf(d2[:, 3:]).all()
    assert (ids[:, :3] >= 0).all() and np.isfinite(d2[:, :3]).all()
    assert (np.diff(d2[:, :3], axis=1) >= 0).all()


def test_sidecar_promotes_to_dense_on_growth():
    """A sidecar tenant whose cloud grows past the threshold promotes to a
    dense placement at the crossing mutation, preserving canonical ids
    (both placements use the identical np.delete/np.concatenate
    indexing)."""
    dispatch.EXEC_CACHE.clear()
    fleet = FleetDaemon(
        [(TenantSpec(name="g", k=4, slo="latency"), _mk_points(40, 34))],
        ServeFleetConfig(min_bucket=8, max_batch=64, warmup=False,
                         sidecar_threshold=64, drr_quantum=16))
    t = fleet.tenants["g"]
    assert t.is_sidecar
    grown = _mk_points(48, 35) + np.float32(1.0)
    [r] = fleet.submit(1, "g", "insert", grown)
    assert r.ok and r.n_points == 88
    assert not t.is_sidecar and t.promotions == 1
    probe = _mk_points(4, 36)
    r2 = _query_through(fleet, 2, "g", probe)
    assert r2.ok
    expected = np.concatenate([_mk_points(40, 34), grown])
    oracle = KnnProblem.prepare(expected, KnnConfig(k=4, adaptive=False))
    ref_i, ref_d = oracle.query(probe, 4)
    np.testing.assert_array_equal(np.asarray(r2.ids), np.asarray(ref_i))
    np.testing.assert_array_equal(np.asarray(r2.d2),
                                  np.asarray(ref_d, np.float32))


# -- replication + failover ---------------------------------------------------

def test_replication_log_sequencing():
    log = ReplicationLog()
    r1 = log.append("insert", np.zeros((2, 3), np.float32))
    r2 = log.append("delete", np.asarray([0]))
    assert (r1.seq, r2.seq, log.committed_seq) == (1, 2, 2)
    assert [r.seq for r in log.since(0)] == [1, 2]
    assert [r.seq for r in log.since(1)] == [2]
    assert log.since(2) == []


def test_replica_refuses_sequence_gap():
    """A gap means the shipper lost a committed delta: the replica must
    raise, never silently reorder or skip."""
    from cuda_knearests_tpu.serve.fleet.replica import DeltaRecord

    problem = KnnProblem.prepare(_mk_points(300, 50),
                                 KnnConfig(k=4, adaptive=False))
    rep = Replica(problem, compact_threshold=64)
    pts = np.full((2, 3), 123.0, np.float32)
    rep.apply(DeltaRecord(seq=1, kind="insert", payload=pts))
    with pytest.raises(RuntimeError, match="sequence gap"):
        rep.apply(DeltaRecord(seq=3, kind="insert", payload=pts))
    with pytest.raises(RuntimeError, match="sequence gap"):
        rep.apply(DeltaRecord(seq=1, kind="insert", payload=pts))  # replay


@pytest.mark.parametrize("ship_mode", ["sync", "lazy"])
def test_in_process_failover_zero_lost_byte_identical(ship_mode):
    """Mutations commit through the log, the primary dies (overlay swap),
    the promoted replica answers byte-identically to a rebuild oracle on
    the committed cloud -- under both ship modes (sync ships each commit;
    lazy defers everything to failover's re-ship)."""
    dispatch.EXEC_CACHE.clear()
    pts0 = _mk_points(600, 51)
    fleet = FleetDaemon(
        [(TenantSpec(name="r", k=6, slo="throughput", replicas=1,
                     ship_mode=ship_mode), pts0)],
        ServeFleetConfig(min_bucket=8, max_batch=64, warmup=False,
                         sidecar_threshold=192, compact_threshold=64,
                         drr_quantum=16))
    ins = _mk_points(5, 52)
    [r1] = fleet.submit(1, "r", "insert", ins)
    assert r1.ok
    [r2] = fleet.submit(2, "r", "delete", np.asarray([3, 7, 11]))
    assert r2.ok
    t = fleet.tenants["r"]
    assert t.log.committed_seq == 2
    if ship_mode == "sync":
        assert t.replica_pool[0].applied_seq == 2
    else:
        assert t.replica_pool[0].applied_seq == 0    # nothing shipped yet
    info = fleet.failover("r")
    assert info["committed_seq"] == 2
    assert info["replayed"] == (0 if ship_mode == "sync" else 2)
    expected = np.delete(np.concatenate([pts0, ins]), [3, 7, 11], axis=0)
    assert t.daemon.overlay.n_points == expected.shape[0]  # zero lost
    probe = _mk_points(6, 53)
    r3 = _query_through(fleet, 3, "r", probe)
    assert r3.ok
    oracle = KnnProblem.prepare(expected, KnnConfig(k=6, adaptive=False))
    ref_i, ref_d = oracle.query(probe, 6)
    np.testing.assert_array_equal(np.asarray(r3.ids), np.asarray(ref_i))
    np.testing.assert_array_equal(np.asarray(r3.d2),
                                  np.asarray(ref_d, np.float32))


def test_failover_without_replica_is_typed():
    dispatch.EXEC_CACHE.clear()
    fleet = FleetDaemon(
        [(TenantSpec(name="solo", k=4, slo="latency"),
          _mk_points(300, 54))],
        ServeFleetConfig(min_bucket=8, max_batch=64, warmup=False,
                         sidecar_threshold=192, drr_quantum=16))
    with pytest.raises(TransportError):
        fleet.failover("solo")


def test_process_level_failover_drill():
    """The acceptance law end to end with REAL child processes: a genuine
    SIGKILL mid-stream, zero lost committed mutations, post-failover
    answers byte-identical to the rebuild oracle (shared by the
    --failover-smoke CLI and the fleet_failover bench row)."""
    drill = failover_drill(n=400, k=6, ops=12, seed=1)
    assert drill["failovers"] >= 1
    assert drill["zero_lost_committed"], drill
    assert drill["post_failover_byte_identical"], drill
    assert drill["failover_ok"]
    assert drill["committed_mutations"] == drill["commits_acked"]


# -- the wire stamp -----------------------------------------------------------

def test_response_tenant_stamp_on_wire():
    r = Response(req_id=1, ok=True, ids=np.zeros((1, 2), np.int32),
                 d2=np.zeros((1, 2), np.float32), tenant="acme")
    assert r.to_wire()["tenant"] == "acme"
    r2 = Response(req_id=2, ok=True, ids=np.zeros((1, 2), np.int32),
                  d2=np.zeros((1, 2), np.float32))
    assert "tenant" not in r2.to_wire()   # single-tenant wires unchanged


# -- static proof hooks (the fleet syncflow windows) --------------------------

def test_fleet_syncflow_windows_proved():
    from cuda_knearests_tpu.analysis import syncflow

    worst = syncflow.worst_case_env()
    batch = syncflow.WINDOWS["fleet-batch"]
    assert syncflow.evaluate(batch.syncs, worst) <= 4   # like serve today
    assert "serve-batch" in batch.includes
    assert syncflow.evaluate(
        syncflow.WINDOWS["fleet-replica-apply"].syncs, worst) == 0
    assert syncflow.evaluate(
        syncflow.WINDOWS["fleet-sidecar"].syncs, worst) == 0
    for route in ("fleet-batch", "fleet-replica-apply", "fleet-sidecar"):
        assert route in syncflow.ROUTE_WINDOWS


# -- fuzz: seeded faults + corpus replay --------------------------------------

# each corruption with a spec shaped so the fault MUST bite: cross-tenant
# needs >= 2 tenants; drop-delta needs a committed mutation shipped before
# failover (sync); stale-replica needs a behind replica (lazy) whose
# re-ship is skipped
_FLEET_FAULT_SPECS = {
    "cross-tenant": dict(replicated=-1, ship_mode="sync"),
    "drop-delta": dict(replicated=1, ship_mode="sync"),
    "stale-replica": dict(replicated=1, ship_mode="lazy"),
}


@pytest.mark.parametrize("fault", sorted(_FLEET_FAULT_SPECS))
def test_fleet_fault_provably_detected(fault, tmp_path, monkeypatch):
    """Each KNTPU_FLEET_FAULT corruption yields a detected, banked failure
    on a stream shaped to reach it -- the campaign's detectors are alive."""
    from cuda_knearests_tpu.fuzz.fleet import FleetSpec, run_fleet_case

    monkeypatch.setenv("KNTPU_FLEET_FAULT", fault)
    spec = FleetSpec(seed=5, n0s=(90, 150), ks=(4, 4), n_ops=6,
                     **_FLEET_FAULT_SPECS[fault])
    failure = run_fleet_case(spec, bank_dir=str(tmp_path), minimize=False)
    assert failure is not None, f"fault {fault} went undetected"
    assert failure.banked and os.path.exists(failure.banked)
    assert failure.banked.endswith("-fleet.npz")


def test_faulted_run_never_banks_into_real_corpus(monkeypatch):
    from cuda_knearests_tpu.fuzz import CORPUS_DIR
    from cuda_knearests_tpu.fuzz.fleet import _safe_bank_dir

    monkeypatch.delenv("KNTPU_FLEET_FAULT", raising=False)
    assert _safe_bank_dir(CORPUS_DIR) == CORPUS_DIR
    monkeypatch.setenv("KNTPU_FLEET_FAULT", "cross-tenant")
    diverted = _safe_bank_dir(CORPUS_DIR)
    assert os.path.abspath(diverted) != os.path.abspath(CORPUS_DIR)


def test_fleet_case_bank_roundtrip(tmp_path):
    from cuda_knearests_tpu.fuzz.fleet import (FleetSpec, bank_fleet_case,
                                               generate_ops,
                                               load_fleet_case)

    spec = FleetSpec(seed=7, n0s=(36, 150), ks=(4, 8), n_ops=5,
                     replicated=1, ship_mode="lazy")
    ops = generate_ops(spec)
    path = bank_fleet_case(str(tmp_path), spec, "mismatch", "why", ops)
    b = load_fleet_case(path)
    assert b["spec"] == spec and b["kind"] == "mismatch"
    assert [o["op"] for o in b["ops"]] == [o["op"] for o in ops]
    for got, want in zip(b["ops"], ops):
        for key in ("points", "ids", "queries"):
            if key in want:
                np.testing.assert_array_equal(got[key], want[key])


def _fleet_corpus_entries():
    return sorted(glob.glob(os.path.join(CORPUS, "*-fleet.npz")))


@pytest.mark.parametrize("path", _fleet_corpus_entries() or ["<empty>"],
                         ids=[os.path.basename(p)
                              for p in _fleet_corpus_entries()] or ["none"])
def test_fleet_corpus_replays_clean(path):
    """Every banked fleet repro must stay fixed (regression pin; the
    corpus is currently allowed to be empty -- the campaign's dev runs
    found no real isolation violations)."""
    if path == "<empty>":
        pytest.skip("no banked fleet repros (campaign clean)")
    from cuda_knearests_tpu.fuzz.fleet import load_fleet_case, replay_ops

    b = load_fleet_case(path)
    got = replay_ops(b["spec"], b["ops"])
    assert got is None, (f"{os.path.basename(path)} regressed: {got} "
                        f"(originally: {b['reason']})")


def test_fleet_campaign_manifest_shape():
    """A tiny clean campaign: manifest fields the smoke and bench stamps
    rely on (rc-0 bar == manifest['ok'])."""
    from cuda_knearests_tpu.fuzz.fleet import run_fleet_campaign

    manifest = run_fleet_campaign(n_cases=2, seed=3, bank_dir=None,
                                  minimize=False, log=None)
    assert manifest["ok"] is True and manifest["failures"] == []
    for key in ("flavor", "requested_cases", "completed_cases", "seed",
                "fault", "elapsed_s", "corpus_size"):
        assert key in manifest
    assert manifest["flavor"] == "fleet-stream"
    assert manifest["fault"] is None


# =============================================================================
# ISSUE 17: elastic pod tenants behind the front door, mesh snapshots +
# SIGKILL failover, and the chaos campaign's detectors
# =============================================================================

# pod placement sits above the dense rung: 48 <= dense < 200 <= pod
POD_CFG = ServeFleetConfig(min_bucket=8, max_batch=64, compact_threshold=32,
                           warmup=False, sidecar_threshold=48,
                           pod_threshold=200, pod_shards=2,
                           pod_skew_threshold=3.0)


def test_pod_tenant_behind_front_door_byte_identity():
    """A tenant above pod_threshold serves from the pod-partitioned
    elastic index behind the SAME front door: mutations commit through
    the replication log, and answers stay byte-identical to the
    rebuild-from-scratch oracle over the mutated cloud (and tie-aware
    correct vs an independent dense rebuild)."""
    tracked = np.array(generate_uniform(260, seed=11))
    fleet = FleetDaemon([(TenantSpec(name="p0", k=6), tracked)], POD_CFG)
    t = fleet.tenants["p0"]
    assert t.is_pod and t.elastic is not None and t.log is not None
    rng = np.random.default_rng(3)
    now = 0.0
    for i in range(9):
        now += 1e-3
        if i % 3 == 2:
            ids = np.sort(rng.choice(t.n_points, size=4,
                                     replace=False)).astype(np.int64)  # kntpu-ok: wide-dtype -- host id payload
            [r] = fleet.submit(i, "p0", "delete", ids, now=now)
            assert r.ok, r.error
            tracked = np.delete(tracked, ids, axis=0)
        else:
            pts = (rng.random((6, 3)) * 110.0 + 5.0).astype(np.float32)
            [r] = fleet.submit(i, "p0", "insert", pts, now=now)
            assert r.ok, r.error
            tracked = np.concatenate([tracked, pts])
    assert t.log.committed_seq == 9
    q = (np.random.default_rng(5).random((24, 3)) * 980.0
         + 10.0).astype(np.float32)
    [r] = fleet.submit(99, "p0", "query", q, now=now + 1e-3)
    assert r.ok and r.tenant == "p0"
    o_i, o_d = t.elastic.rebuild_oracle_query(q, 6)
    np.testing.assert_array_equal(np.asarray(r.ids), o_i)
    np.testing.assert_array_equal(np.asarray(r.d2), o_d)
    ref = KnnProblem.prepare(tracked, KnnConfig(k=6, adaptive=False),
                             validate=False)
    _ri, ref_d = ref.query(q, 6)
    assert check_route_result(tracked, q, np.asarray(r.ids),
                              np.asarray(r.d2), np.asarray(ref_d),
                              6) is None


def test_dense_tenant_promotes_to_pod_and_log_carries_over():
    """A dense tenant that grows past pod_threshold promotes to the
    elastic placement through the front door; the replication log (the
    mesh-durability record) carries over -- committed seq is placement-
    independent -- and post-promotion answers match the oracle."""
    pts = generate_uniform(190, seed=2)          # dense: 48 <= 190 < 200
    fleet = FleetDaemon([(TenantSpec(name="g", k=4, replicas=1), pts)],
                        POD_CFG)
    t = fleet.tenants["g"]
    assert not t.is_pod and t.daemon is not None
    out = fleet.submit(1, "g", "insert", generate_uniform(16, seed=3),
                       now=0.001)
    assert out[-1].ok
    assert t.is_pod and t.promotions == 1
    assert t.log is not None and t.log.committed_seq == 1
    assert t.n_points == 206
    q = (np.random.default_rng(8).random((12, 3)) * 980.0
         + 10.0).astype(np.float32)
    [r] = fleet.submit(2, "g", "query", q, now=0.002)
    assert r.ok
    o_i, o_d = t.elastic.rebuild_oracle_query(q, 4)
    np.testing.assert_array_equal(np.asarray(r.ids), o_i)
    np.testing.assert_array_equal(np.asarray(r.d2), o_d)


def test_pod_tenant_refuses_fof_typed():
    """FoF against a pod tenant refuses typed (invalid-input): the pod
    placement serves scatter-gather kNN only."""
    fleet = FleetDaemon(
        [(TenantSpec(name="p0", k=4), generate_uniform(220, seed=7))],
        POD_CFG)
    assert fleet.tenants["p0"].is_pod
    [r] = fleet.submit(1, "p0", "fof", 10.0, now=0.001)
    assert not r.ok
    assert r.failure_kind == "invalid-input"
    assert "pod" in r.error
    assert classify_fault_text(f"InvalidRequestError: {r.error}") \
        == "invalid-input"


# -- mesh snapshots + cross-mesh SIGKILL failover -----------------------------

def test_mesh_snapshot_roundtrip_and_typed_refusals(tmp_path):
    """snapshot_tenant round-trips the canonical cloud + committed seq;
    load_snapshot refuses torn/corrupt/stale files typed (a standby mesh
    NEVER promotes from a refused snapshot)."""
    from cuda_knearests_tpu.serve.fleet.elastic import (SNAPSHOT_SCHEMA,
                                                        load_snapshot,
                                                        snapshot_tenant)
    from cuda_knearests_tpu.utils.memory import CorruptInputError

    fleet = FleetDaemon(
        [(TenantSpec(name="p0", k=5), generate_uniform(230, seed=4))],
        POD_CFG)
    t = fleet.tenants["p0"]
    [r] = fleet.submit(1, "p0", "insert", generate_uniform(8, seed=5),
                       now=0.001)
    assert r.ok
    info = snapshot_tenant(t, str(tmp_path / "mesh"))
    assert info["committed_seq"] == 1 and info["n_points"] == 238
    snap = load_snapshot(info["path"])
    np.testing.assert_array_equal(snap["points"], t.mutated_points())
    assert snap["committed_seq"] == 1 and snap["k"] == 5
    assert snap["nshards"] == 2 and snap["sha256"] == info["sha256"]

    # refusal 1: unreadable garbage
    bad = tmp_path / "garbage.npz"
    bad.write_bytes(b"definitely not a zip archive")
    with pytest.raises(CorruptInputError, match="unreadable"):
        load_snapshot(str(bad))
    # refusal 2: missing envelope (sha256 stripped)
    fields = dict(np.load(info["path"]))
    stripped = {k: v for k, v in fields.items() if k != "sha256"}
    np.savez_compressed(tmp_path / "stripped.npz", **stripped)
    with pytest.raises(CorruptInputError, match="envelope"):
        load_snapshot(str(tmp_path / "stripped.npz"))
    # refusal 3: stale schema tag (digest recomputed, so ONLY the schema
    # check can fire)
    from cuda_knearests_tpu.serve.fleet import elastic as _elastic
    stale = dict(fields)
    stale["schema"] = np.bytes_(b"kntpu-mesh-snapshot-v0")
    del stale["sha256"]
    stale["sha256"] = np.bytes_(
        _elastic._snapshot_digest(stale).encode())
    np.savez_compressed(tmp_path / "stale.npz", **stale)
    with pytest.raises(CorruptInputError, match="stale or unknown schema"):
        load_snapshot(str(tmp_path / "stale.npz"))
    # refusal 4: flipped payload bit -> checksum mismatch
    torn = dict(fields)
    pts = np.array(torn["points"])
    pts[0, 0] += 1.0
    torn["points"] = pts
    np.savez_compressed(tmp_path / "torn.npz", **torn)
    with pytest.raises(CorruptInputError, match="checksum mismatch"):
        load_snapshot(str(tmp_path / "torn.npz"))
    assert SNAPSHOT_SCHEMA.startswith("kntpu-mesh-snapshot-")


def test_mesh_failover_drill_sigkill_mid_migration():
    """The cross-mesh drill: standby promotes from snapshot + committed-
    log replay after a genuine mid-migration SIGKILL of the primary;
    zero committed mutations lost, post-failover answers byte-identical
    to the parent-side rebuild oracle."""
    from cuda_knearests_tpu.serve.fleet.elastic import mesh_failover_drill

    drill = mesh_failover_drill(n=900, k=6, ops=26, seed=0, log=None)
    assert drill["killed_mid_migration"] is True
    assert drill["mesh_failovers"] >= 1
    assert drill["zero_lost_committed"] is True
    assert drill["post_failover_byte_identical"] is True
    assert drill["mesh_failover_ok"] is True
    assert drill["replay_tail"] >= drill["snapshot_seq"]
    assert set(drill["latency_decomposition"]) == {
        "total_ms", "queue_ms", "dispatch_ms", "device_ms"}


# -- chaos fuzz: seeded faults + corpus replay --------------------------------

@pytest.mark.parametrize("fault", ["lost-range", "torn-migration"])
def test_chaos_fault_provably_detected(fault, tmp_path, monkeypatch):
    """Each migration-corrupting KNTPU_FLEET_FAULT yields a detected,
    banked chaos failure: the guaranteed hotspot -> rebalance -> pump
    tail reaches a handover, and the shard-population conservation
    invariant catches the torn/lost range even when no probe lands near
    the lost rows."""
    from cuda_knearests_tpu.fuzz.chaos import ChaosSpec, run_chaos_case

    monkeypatch.setenv("KNTPU_FLEET_FAULT", fault)
    spec = ChaosSpec(seed=5, n0=200, dense_n0=90, k=4, nshards=2, n_ops=6)
    failure = run_chaos_case(spec, bank_dir=str(tmp_path), minimize=False)
    assert failure is not None, f"fault {fault} went undetected"
    assert failure.banked and os.path.exists(failure.banked)
    assert failure.banked.endswith("-chaos.npz")
    assert "conservation" in failure.reason \
        or "lost or duplicated" in failure.reason \
        or "diverged" in failure.reason


def test_chaos_case_bank_roundtrip(tmp_path):
    from cuda_knearests_tpu.fuzz.chaos import (ChaosSpec, bank_chaos_case,
                                               generate_ops,
                                               load_chaos_case)

    spec = ChaosSpec(seed=9, n0=200, dense_n0=90, k=4, nshards=2, n_ops=8)
    ops = generate_ops(spec)
    assert any(o["op"] == "rebalance" for o in ops)   # the guaranteed tail
    path = bank_chaos_case(str(tmp_path), spec, "mismatch", "why", ops)
    b = load_chaos_case(path)
    assert b["spec"] == spec and b["kind"] == "mismatch"
    assert [o["op"] for o in b["ops"]] == [o["op"] for o in ops]
    for got, want in zip(b["ops"], ops):
        for key in ("points", "ids", "queries", "n", "shard", "pumps"):
            if key in want:
                np.testing.assert_array_equal(got[key], want[key])


def _chaos_corpus_entries():
    return sorted(glob.glob(os.path.join(CORPUS, "*-chaos.npz")))


@pytest.mark.parametrize("path", _chaos_corpus_entries() or ["<empty>"],
                         ids=[os.path.basename(p)
                              for p in _chaos_corpus_entries()] or ["none"])
def test_chaos_corpus_replays_clean(path):
    """Every banked chaos repro must stay fixed (regression pin; the
    corpus is currently allowed to be empty -- the campaign's dev runs
    found no real divergence under the fault schedules)."""
    if path == "<empty>":
        pytest.skip("no banked chaos repros (campaign clean)")
    from cuda_knearests_tpu.fuzz.chaos import load_chaos_case, replay_ops

    b = load_chaos_case(path)
    got = replay_ops(b["spec"], b["ops"])
    assert got is None, (f"{os.path.basename(path)} regressed: {got} "
                        f"(originally: {b['reason']})")


def test_chaos_campaign_manifest_shape():
    """A tiny clean campaign (no cross-mesh drill: tier-1 keeps that in
    its own test): manifest fields the smoke and bench stamps rely on."""
    from cuda_knearests_tpu.fuzz.chaos import run_chaos_campaign

    manifest = run_chaos_campaign(n_cases=2, seed=3, bank_dir=None,
                                  minimize=False, drill=False, log=None)
    assert manifest["ok"] is True and manifest["failures"] == []
    for key in ("flavor", "requested_cases", "completed_cases", "seed",
                "fault", "elapsed_s", "corpus_size", "mesh_failover"):
        assert key in manifest
    assert manifest["flavor"] == "chaos-stream"
    assert manifest["fault"] is None and manifest["mesh_failover"] is None
