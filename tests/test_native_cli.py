"""Native oracle CLI: builds and runs end-to-end on the reference fixture."""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ORACLE_DIR = os.path.join(REPO, "oracle")
FIXTURE = "/root/reference/pts20K.xyz"


@pytest.mark.skipif(shutil.which("make") is None or shutil.which("g++") is None,
                    reason="no native toolchain")
@pytest.mark.skipif(not os.path.exists(FIXTURE), reason="fixture not mounted")
def test_oracle_cli_runs():
    subprocess.run(["make", "-C", ORACLE_DIR, "-s", "oracle_cli"], check=True)
    out = subprocess.run([os.path.join(ORACLE_DIR, "oracle_cli"), FIXTURE, "5"],
                         check=True, capture_output=True, text=True).stdout
    assert "loaded 20626 points" in out
    assert "knn cpu:" in out
    assert "checksum:" in out
    # deterministic: same input -> same checksum across runs
    out2 = subprocess.run([os.path.join(ORACLE_DIR, "oracle_cli"), FIXTURE, "5"],
                          check=True, capture_output=True, text=True).stdout
    line = [l for l in out.splitlines() if l.startswith("checksum")][0]
    line2 = [l for l in out2.splitlines() if l.startswith("checksum")][0]
    assert line == line2


def test_profiling_trace_smoke(tmp_path):
    import jax.numpy as jnp

    from cuda_knearests_tpu.utils.profiling import annotate, trace

    with trace(str(tmp_path)):
        with annotate("smoke"):
            (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    # a trace directory with at least one artifact appears
    produced = list(tmp_path.rglob("*"))
    assert produced, "profiler produced no artifacts"
