"""Native oracle CLI: builds and runs end-to-end on the reference fixture.
Plus the python CLI's input-contract exit path (rc 5, ISSUE 4): typed
refusals must exit distinctly from device errors (rc 4) and engine
mismatches (rc 1), with failure_kind stamped machine-readably."""

import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ORACLE_DIR = os.path.join(REPO, "oracle")
FIXTURE = "/root/reference/pts20K.xyz"


def _run_cli(*args, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "cuda_knearests_tpu.cli", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def _summary_line(stdout: str) -> dict:
    lines = [l for l in stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON summary line in: {stdout!r}"
    return json.loads(lines[-1])


def test_cli_nonfinite_input_exits_rc5(tmp_path):
    """A NaN coordinate in the input file is an input-contract refusal:
    rc 5, failure_kind='invalid-input' on the machine-readable line --
    mirroring the rc-4 device-error path, but distinctly caller-fixable."""
    bad = tmp_path / "nan.xyz"
    bad.write_text("3\n1 2 3\nnan 5 6\n7 8 9\n")
    r = _run_cli(str(bad), "--k", "2")
    assert r.returncode == 5, r.stdout + r.stderr
    summary = _summary_line(r.stdout)
    assert summary["failure_kind"] == "invalid-input"
    assert "REFUSED [invalid-input]" in r.stderr


def test_cli_corrupt_header_exits_rc5(tmp_path):
    """An .xyz whose header count disagrees with its rows refuses rc 5
    (CorruptInputError), not a raw traceback."""
    bad = tmp_path / "short.xyz"
    bad.write_text("5\n0 0 0\n1 1 1\n")
    r = _run_cli(str(bad), "--k", "2")
    assert r.returncode == 5, r.stdout + r.stderr
    assert _summary_line(r.stdout)["failure_kind"] == "invalid-input"


def test_cli_invalid_k_exits_rc5(tmp_path):
    good = tmp_path / "ok.xyz"
    good.write_text("2\n1 2 3\n4 5 6\n")
    r = _run_cli(str(good), "--k", "0")
    assert r.returncode == 5, r.stdout + r.stderr
    summary = _summary_line(r.stdout)
    assert summary["failure_kind"] == "invalid-input"
    assert "k must be" in summary["error"]


@pytest.mark.skipif(shutil.which("make") is None or shutil.which("g++") is None,
                    reason="no native toolchain")
@pytest.mark.skipif(not os.path.exists(FIXTURE), reason="fixture not mounted")
def test_oracle_cli_runs():
    subprocess.run(["make", "-C", ORACLE_DIR, "-s", "oracle_cli"], check=True)
    out = subprocess.run([os.path.join(ORACLE_DIR, "oracle_cli"), FIXTURE, "5"],
                         check=True, capture_output=True, text=True).stdout
    assert "loaded 20626 points" in out
    assert "knn cpu:" in out
    assert "checksum:" in out
    # deterministic: same input -> same checksum across runs
    out2 = subprocess.run([os.path.join(ORACLE_DIR, "oracle_cli"), FIXTURE, "5"],
                          check=True, capture_output=True, text=True).stdout
    line = [l for l in out.splitlines() if l.startswith("checksum")][0]
    line2 = [l for l in out2.splitlines() if l.startswith("checksum")][0]
    assert line == line2


def test_profiling_trace_smoke(tmp_path):
    import jax.numpy as jnp

    from cuda_knearests_tpu.utils.profiling import annotate, trace

    with trace(str(tmp_path)):
        with annotate("smoke"):
            (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    # a trace directory with at least one artifact appears
    produced = list(tmp_path.rglob("*"))
    assert produced, "profiler produced no artifacts"
