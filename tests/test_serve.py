"""Serving subsystem acceptance: batching law, delta-overlay byte-identity,
zero-recompile steady state, typed failure containment.

The ISSUE 6 gates pinned here:

  * the steady-state serving loop performs ZERO recompiles after warmup,
    asserted via the ExecutableCache counters on the 20k fixture;
  * incremental insert/delete + query results are byte-identical to a full
    re-prepare on the mutated cloud, for both the delta-overlay and the
    post-compaction states;
  * a crashed or refused request costs one batch (typed failure mapped
    onto FAILURE_KINDS), never the daemon.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cuda_knearests_tpu import KnnConfig, KnnProblem
from cuda_knearests_tpu.config import ServeConfig
from cuda_knearests_tpu.io import generate_uniform
from cuda_knearests_tpu.runtime import dispatch
from cuda_knearests_tpu.runtime.supervisor import FAILURE_KINDS
from cuda_knearests_tpu.serve import (DeltaOverlay, DynamicBatcher, LoadSpec,
                                      Request, ServeDaemon, build_schedule,
                                      run_session)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def served_20k(pts20k):
    """One legacy-route problem over the 20k fixture (the serving pin:
    its query launches ride the executable cache)."""
    return KnnProblem.prepare(pts20k, KnnConfig(k=10, adaptive=False))


# -- ServeConfig: the bucket ladder -------------------------------------------

def test_bucket_ladder():
    cfg = ServeConfig(max_batch=100, min_bucket=8)
    assert cfg.buckets() == (8, 16, 32, 64, 128)
    assert cfg.bucket_for(1) == 8
    assert cfg.bucket_for(9) == 16
    assert cfg.bucket_for(100) == 128


def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(max_batch=4, min_bucket=8)
    with pytest.raises(ValueError):
        ServeConfig(max_delay_s=-1.0)


# -- DynamicBatcher: the flush law (synthetic time) ---------------------------

def _req(i, m, t, k=10):
    return Request(req_id=i, queries=np.zeros((m, 3), np.float32), k=k,
                   arrived_at=t)


def test_batcher_size_trigger():
    b = DynamicBatcher(ServeConfig(max_batch=32, max_delay_s=100.0))
    assert b.admit(_req(1, 20, 0.0), 0.0) == []
    out = b.admit(_req(2, 20, 0.1), 0.1)   # 40 > 32: flush the first alone
    assert len(out) == 1 and out[0].total == 20 and out[0].reason == "size"
    assert b.pending_queries == 20
    out = b.admit(_req(3, 12, 0.2), 0.2)   # exactly full: eager flush
    assert len(out) == 1 and out[0].total == 32
    assert out[0].capacity == 32 and out[0].occupancy == 1.0


def test_batcher_deadline_trigger():
    b = DynamicBatcher(ServeConfig(max_batch=64, max_delay_s=0.5))
    assert b.admit(_req(1, 4, 10.0), 10.0) == []
    assert b.poll(10.2) is None            # not due yet
    assert b.next_deadline() == 10.5
    flushed = b.poll(10.6)
    assert flushed is not None and flushed.reason == "deadline"
    assert flushed.total == 4 and flushed.capacity == 8  # min bucket pad


def test_batcher_barrier_and_drain():
    b = DynamicBatcher(ServeConfig(max_batch=64, max_delay_s=100.0))
    b.admit(_req(1, 4, 0.0), 0.0)
    flushed = b.flush("barrier", 0.1)
    assert flushed.reason == "barrier" and flushed.total == 4
    assert b.flush("drain", 0.2) is None   # empty: nothing to drain
    assert b.flushes == {"size": 0, "deadline": 0, "barrier": 1, "drain": 0}


# -- delta overlay: byte-identity vs rebuild-from-scratch (acceptance) --------

def test_overlay_byte_identical_to_rebuild(served_20k, rng):
    """THE incremental-update gate: after interleaved deletes and inserts,
    overlay answers are byte-identical to a full re-prepare of the mutated
    cloud -- in the delta-overlay state AND after compaction."""
    ov = DeltaOverlay(served_20k, compact_threshold=10 ** 6)
    n0 = served_20k.grid.n_points
    ov.delete(np.sort(rng.choice(n0, 60, replace=False)))
    ov.insert((rng.random((90, 3)) * 990 + 5).astype(np.float32))
    ov.delete(np.sort(rng.choice(ov.n_points, 10, replace=False)))
    queries = generate_uniform(400, seed=77)
    got_i, got_d = ov.query(queries, 10)

    rebuild = served_20k.with_points(ov.mutated_points())
    ref_i, ref_d = rebuild.query(queries, 10)
    np.testing.assert_array_equal(got_i, ref_i)
    np.testing.assert_array_equal(got_d, ref_d)
    assert ov.stats.resolved_rows > 0      # tombstones actually exercised
    assert ov.stats.delta_launches > 0     # and the delta merge

    ov.compact()                            # fold into a re-prepare
    assert ov.mutations_pending == 0
    got2_i, got2_d = ov.query(queries, 10)
    np.testing.assert_array_equal(got2_i, ref_i)
    np.testing.assert_array_equal(got2_d, ref_d)


def test_overlay_compaction_threshold_triggers(uniform_10k):
    p = KnnProblem.prepare(uniform_10k, KnnConfig(k=8, adaptive=False))
    ov = DeltaOverlay(p, compact_threshold=16)
    ov.insert((np.random.default_rng(3).random((16, 3)) * 990 + 5)
              .astype(np.float32))
    assert ov.stats.compactions == 1 and ov.mutations_pending == 0
    assert ov.n_points == 10_016


def test_overlay_degraded_small_cloud():
    """k > n_alive: pad contract (-1/inf) must match the rebuild's."""
    pts = generate_uniform(6, seed=2)
    p = KnnProblem.prepare(pts, KnnConfig(k=5, adaptive=False))
    ov = DeltaOverlay(p, compact_threshold=10 ** 6)
    ov.delete(np.array([0, 1, 2, 3]))
    queries = generate_uniform(7, seed=3)
    got_i, got_d = ov.query(queries, 5)
    ref_i, ref_d = p.with_points(ov.mutated_points()).query(queries, 5)
    np.testing.assert_array_equal(got_i, ref_i)
    np.testing.assert_array_equal(got_d, ref_d)
    assert (got_i[:, 2:] == -1).all() and np.isinf(got_d[:, 2:]).all()


def test_overlay_dirty_cell_skip(uniform_10k):
    """A mutation far from every query is pruned by the dirty-cell bound:
    the delta launch is skipped outright, and results are still exact."""
    p = KnnProblem.prepare(uniform_10k, KnnConfig(k=4, adaptive=False))
    ov = DeltaOverlay(p, compact_threshold=10 ** 6)
    ov.insert(np.full((4, 3), 995.0, np.float32))   # one far corner
    queries = (np.random.default_rng(9).random((64, 3)) * 40.0
               ).astype(np.float32)                  # opposite corner
    got_i, got_d = ov.query(queries, 4)
    assert ov.stats.delta_skips == 1 and ov.stats.delta_launches == 0
    ref_i, ref_d = p.with_points(ov.mutated_points()).query(queries, 4)
    np.testing.assert_array_equal(got_i, ref_i)
    np.testing.assert_array_equal(got_d, ref_d)


# -- zero recompiles in steady state (acceptance, 20k fixture) ----------------

def test_steady_state_zero_recompiles(served_20k):
    """After the daemon's warmup pass over the capacity-bucket ladder, a
    whole open-loop session must hit only cached executables: the
    ExecutableCache miss counter may not move."""
    dispatch.EXEC_CACHE.clear()
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=128,
                                                 max_delay_s=0.003))
    if not dispatch.EXEC_CACHE.enabled:
        pytest.fail("executable cache disabled on CPU -- the serving "
                    "zero-recompile law has no counter to assert against: "
                    f"{dispatch.EXEC_CACHE.disabled_by}")
    warm = dispatch.EXEC_CACHE.stats_dict()
    assert warm["exec_cache_misses"] >= len(daemon.config.buckets())
    summary = run_session(daemon, LoadSpec(rate=600.0, requests=150, seed=6))
    assert summary["batches"] >= 1
    assert summary["recompiles"] == 0, summary
    assert summary["failed_requests"] == 0 and summary["refused"] == 0
    assert summary["exec_cache_hits"] > warm["exec_cache_hits"]
    assert summary["completed_queries"] > 0
    assert summary["p50_ms"] is not None and summary["p99_ms"] is not None
    assert summary["sustained_qps"] > 0


# -- containment: a crashed/refused request costs one batch, not the daemon --

def test_batch_fault_contained_typed(served_20k, monkeypatch):
    monkeypatch.setenv("KNTPU_SERVE_FAULT", "batch:0")
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=64,
                                                 max_delay_s=0.001))
    queries = generate_uniform(8, seed=11)
    out = daemon.submit(1, "query", queries)
    out += daemon.drain()
    assert len(out) == 1 and not out[0].ok
    assert out[0].failure_kind in FAILURE_KINDS
    assert out[0].failure_kind == "crash"
    assert daemon.failed_batches == 1
    # the daemon SURVIVES: the next batch executes normally
    out2 = daemon.submit(2, "query", queries)
    out2 += daemon.drain()
    assert len(out2) == 1 and out2[0].ok
    assert out2[0].ids.shape == (8, 10)


def test_batch_fault_oom_kind(served_20k, monkeypatch):
    monkeypatch.setenv("KNTPU_SERVE_FAULT", "batch:0:oom")
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=64,
                                                 max_delay_s=0.001))
    out = daemon.submit(1, "query", generate_uniform(4, seed=12))
    out += daemon.drain()
    assert not out[0].ok and out[0].failure_kind == "oom"
    assert daemon.failure_kinds == {"oom": 1}


def test_refusal_typed_and_isolated(served_20k):
    """A malformed request refuses typed (kind 'invalid-input') at
    admission and costs nothing else -- pending work still completes."""
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=64,
                                                 max_delay_s=0.001))
    good = generate_uniform(4, seed=13)
    daemon.submit(1, "query", good)                       # pending
    bad = np.full((3, 3), -42.0, np.float32)              # out of domain
    refusals = daemon.submit(2, "query", bad)
    assert len(refusals) == 1 and not refusals[0].ok
    assert refusals[0].failure_kind == "invalid-input"
    assert "domain" in refusals[0].error.lower()
    assert daemon.refused == 1
    done = daemon.drain()
    assert len(done) == 1 and done[0].ok and done[0].req_id == 1


def test_refusal_matrix(served_20k):
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=32,
                                                 max_delay_s=0.001))
    cases = [
        ("query", np.zeros((4, 2), np.float32), None),        # bad shape
        ("query", generate_uniform(4, seed=1), 99),           # k > serving k
        ("query", generate_uniform(64, seed=1), None),        # > max_batch
        ("insert", np.full((2, 3), np.nan, np.float32), None),  # non-finite
        ("delete", np.array([0.5, 1.5]), None),               # float ids
        ("delete", np.array([10 ** 9]), None),                # out of range
        ("delete", np.array([1, 1]), None),                   # duplicates
        ("frobnicate", np.zeros((1, 3), np.float32), None),   # unknown kind
    ]
    for i, (kind, payload, k) in enumerate(cases):
        out = daemon.submit(i, kind, payload, k=k)
        assert len(out) == 1 and not out[0].ok, (kind, payload)
        assert out[0].failure_kind == "invalid-input"
    assert daemon.refused == len(cases)
    assert daemon.failed_batches == 0


# -- mutation barriers + per-request k ----------------------------------------

def test_mutation_is_barrier(served_20k):
    """Queries pending at a mutation's arrival flush FIRST (they answer
    against the pre-mutation cloud)."""
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=64,
                                                 max_delay_s=100.0))
    daemon.submit(1, "query", generate_uniform(4, seed=14))
    n_before = daemon.overlay.n_points
    out = daemon.submit(2, "insert",
                        (np.random.default_rng(5).random((6, 3)) * 990 + 5)
                        .astype(np.float32))
    assert [r.req_id for r in out] == [1, 2]
    assert out[0].ok and out[1].ok
    assert out[1].n_points == n_before + 6
    assert daemon.batcher.flushes["barrier"] == 1
    # the flushed query's neighbor ids predate the insert: all < n_before
    assert (out[0].ids < n_before).all()


def test_per_request_k_truncates(served_20k):
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=64,
                                                 max_delay_s=0.001))
    queries = generate_uniform(5, seed=15)
    full = daemon.submit(1, "query", queries) + daemon.drain()
    small = daemon.submit(2, "query", queries, k=3) + daemon.drain()
    assert full[0].ids.shape == (5, 10) and small[0].ids.shape == (5, 3)
    np.testing.assert_array_equal(small[0].ids, full[0].ids[:, :3])
    np.testing.assert_array_equal(small[0].d2, full[0].d2[:, :3])


# -- open-loop load generator -------------------------------------------------

def test_schedule_is_seeded_and_open_loop():
    spec = LoadSpec(rate=100.0, requests=40, mutation_ratio=0.3, seed=9)
    s1 = build_schedule(spec, n_current=1000)
    s2 = build_schedule(spec, n_current=1000)
    assert len(s1) == 40
    times = [item["t"] for item in s1]
    assert times == sorted(times)           # arrivals pre-scheduled, ordered
    assert [i["kind"] for i in s1] == [i["kind"] for i in s2]
    for a, b in zip(s1, s2):
        np.testing.assert_array_equal(a["payload"], b["payload"])
    kinds = {i["kind"] for i in s1}
    assert "query" in kinds and kinds & {"insert", "delete"}


def test_mutating_session_end_to_end(uniform_10k):
    """Mutations ride the live loop: inserts/deletes apply as barriers,
    every response lands, and the overlay's cloud tracks the net size."""
    p = KnnProblem.prepare(uniform_10k, KnnConfig(k=8, adaptive=False))
    daemon = ServeDaemon(p, ServeConfig(max_batch=64, max_delay_s=0.002))
    spec = LoadSpec(rate=500.0, requests=60, mutation_ratio=0.3, seed=10)
    summary = run_session(daemon, spec)
    assert summary["responses"] == summary["requests"]
    assert summary["failed_requests"] == 0 and summary["refused"] == 0
    net = (summary["overlay_inserts"] - summary["overlay_deletes"])
    assert summary["n_points"] == 10_000 + net


# -- the daemon front door ----------------------------------------------------

def test_stdio_daemon_roundtrip():
    """The JSON-lines wire surface end to end in a subprocess: queries
    answer, mutations apply, malformed requests refuse typed."""
    reqs = [
        {"id": 1, "op": "query",
         "data": (generate_uniform(3, seed=21) * 1.0).tolist(), "k": 4},
        {"id": 2, "op": "insert",
         "data": (generate_uniform(2, seed=22) * 1.0).tolist()},
        {"id": 3, "op": "delete", "data": [0, 5]},
        {"id": 4, "op": "query", "data": [[-1.0, 0.0, 0.0]]},  # refusal
    ]
    payload = "\n".join(json.dumps(r) for r in reqs) + "\n"
    proc = subprocess.run(
        [sys.executable, "-m", "cuda_knearests_tpu.serve",
         "--points", "uniform:600", "--k", "6", "--max-delay-ms", "1"],
        input=payload, capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines() if ln.strip()]
    by_id = {ln["id"]: ln for ln in lines}
    assert by_id[1]["ok"] and len(by_id[1]["ids"]) == 3
    assert len(by_id[1]["ids"][0]) == 4
    assert by_id[2]["ok"] and by_id[2]["n_points"] == 602
    assert by_id[3]["ok"] and by_id[3]["n_points"] == 600
    assert not by_id[4]["ok"]
    assert by_id[4]["failure_kind"] == "invalid-input"


def test_loadgen_cli_assert_steady():
    """The check.sh smoke's exact invocation: rc 0, >= 1 batch, zero
    steady-state recompiles."""
    proc = subprocess.run(
        [sys.executable, "-m", "cuda_knearests_tpu.serve", "--loadgen",
         "--points", "uniform:2000", "--requests", "30", "--rate", "300",
         "--seed", "0", "--assert-steady"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr[-2000:]
    summary = json.loads(proc.stdout.splitlines()[-1])
    assert summary["recompiles"] == 0 and summary["batches"] >= 1


# -- bench rows (ISSUE 6 acceptance: --serve emits QPS + latency rows) --------

def test_bench_serve_contained_fault_row():
    """The bench row that demonstrates the containment law: the injected
    batch fault costs exactly one typed batch, the malformed request
    refuses typed, and the session still completes with QPS + latency."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    row = bench.serve_scenario("serve_20k_contained_fault")
    assert row["unit"] == "queries/sec" and row["value"] > 0
    assert row["p50_ms"] is not None and row["p99_ms"] is not None
    assert row["failed_batches"] == 1
    assert row["failure_kinds"] == {"oom": 1}
    assert row["failed_requests"] >= 1       # the fault batch's riders
    assert row["refusal_typed"] and row["containment_ok"]
    assert row["completed_queries"] > 0      # the daemon kept serving
    assert "host_syncs" in row and "recompiles" in row


def test_cli_serve_mode(capsys):
    """`python -m cuda_knearests_tpu.cli <pts> --serve RATE` runs the load
    harness against the prepared cloud and emits the serving summary."""
    from cuda_knearests_tpu import cli

    rc = cli.main(["pts20K.xyz", "--k", "6", "--serve", "400",
                   "--serve-requests", "40"])
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert rc == 0
    assert summary["mode"] == "serve" and summary["k"] == 6
    assert summary["sustained_qps"] > 0 and summary["batches"] >= 1
    assert summary["failed_requests"] == 0


def test_mutation_apply_failure_contained(served_20k, monkeypatch):
    """A mutation whose apply dies (e.g. compaction's re-prepare raising)
    costs THAT request one typed failure; the daemon keeps serving."""
    daemon = ServeDaemon(served_20k, ServeConfig(max_batch=64,
                                                 max_delay_s=0.001))

    def boom(points):
        raise RuntimeError("synthetic re-prepare death")

    monkeypatch.setattr(daemon.overlay, "insert", boom)
    out = daemon.submit(1, "insert", generate_uniform(2, seed=30))
    assert len(out) == 1 and not out[0].ok
    assert out[0].failure_kind == "crash"
    assert daemon.failed_mutations == 1
    # daemon survives: queries and real mutations still work
    ok = daemon.submit(2, "query", generate_uniform(3, seed=31)) \
        + daemon.drain()
    assert ok[-1].ok and ok[-1].ids.shape == (3, 10)


def test_wire_is_strict_json():
    """Pad slots (k > n neighbors) must serialize as null, never the
    non-RFC Infinity token -- strict parsers consume the wire."""
    from cuda_knearests_tpu.serve.daemon import Response

    r = Response(req_id=7, ok=True,
                 ids=np.array([[3, -1]], np.int32),
                 d2=np.array([[1.5, np.inf]], np.float32))
    text = json.dumps(r.to_wire())
    assert "Infinity" not in text

    def _reject(tok):
        raise AssertionError(f"non-RFC token on the wire: {tok}")

    wire = json.loads(text, parse_constant=_reject)  # strict-parser stand-in
    assert wire["d2"] == [[1.5, None]] and wire["ids"] == [[3, -1]]


def test_delta_csr_gathers_only_surviving_cells(uniform_10k):
    """The pruned delta launch scores only CSR-gathered rows from cells
    some query's bound could not drop: a far-corner insert contributes
    zero candidates to near-corner queries even when a co-located insert
    forces a launch."""
    p = KnnProblem.prepare(uniform_10k, KnnConfig(k=4, adaptive=False))
    ov = DeltaOverlay(p, compact_threshold=10 ** 6)
    ov.insert(np.full((32, 3), 995.0, np.float32))   # far corner
    ov.insert(np.full((2, 3), 20.0, np.float32))     # among the queries
    queries = (np.random.default_rng(9).random((64, 3)) * 40.0
               ).astype(np.float32)
    got_i, got_d = ov.query(queries, 4)
    assert ov.stats.delta_launches == 1
    assert ov.stats.delta_candidates == 2            # far corner pruned
    ref_i, ref_d = p.with_points(ov.mutated_points()).query(queries, 4)
    np.testing.assert_array_equal(got_i, ref_i)
    np.testing.assert_array_equal(got_d, ref_d)


def test_stdio_burst_on_held_open_pipe():
    """Requests written in ONE burst on a pipe that stays open must all be
    answered (the select-vs-buffered-readline stranding bug): responses
    arrive without the client sending more bytes or closing stdin."""
    import select as _select

    reqs = [{"id": i, "op": "query",
             "data": generate_uniform(2, seed=40 + i).tolist(), "k": 4}
            for i in range(3)]
    proc = subprocess.Popen(
        [sys.executable, "-m", "cuda_knearests_tpu.serve",
         "--points", "uniform:500", "--k", "4", "--max-delay-ms", "2"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    try:
        proc.stdin.write("".join(json.dumps(r) + "\n" for r in reqs))
        proc.stdin.flush()                       # pipe stays OPEN
        got = {}
        # read the RAW fd (select + buffered readline would strand
        # coalesced responses in the client's buffer -- the mirror image
        # of the daemon-side bug this test pins)
        fd = proc.stdout.fileno()
        buf = b""
        deadline = 180.0
        import time as _time
        t0 = _time.monotonic()
        while len(got) < 3 and _time.monotonic() - t0 < deadline:
            while b"\n" in buf:
                raw, buf = buf.split(b"\n", 1)
                if raw.strip():
                    r = json.loads(raw)
                    got[r["id"]] = r
            if len(got) >= 3:
                break
            if _select.select([fd], [], [], 1.0)[0]:
                chunk = os.read(fd, 1 << 16)
                if not chunk:
                    break
                buf += chunk
        assert sorted(got) == [0, 1, 2], \
            f"only {sorted(got)} answered before stdin closed"
        assert all(r["ok"] for r in got.values())
    finally:
        proc.stdin.close()
        proc.wait(timeout=60)
    assert proc.returncode == 0


def test_insert_preserves_alive_caches(uniform_10k):
    """Inserts must not invalidate the O(n) alive-set caches (only the
    tombstone mask feeds them); deletes must."""
    p = KnnProblem.prepare(uniform_10k, KnnConfig(k=4, adaptive=False))
    ov = DeltaOverlay(p, compact_threshold=10 ** 6)
    sentinel_cache, sentinel_map = ("pts", "ids"), np.arange(3)
    ov._alive_cache = sentinel_cache
    ov._old2new = sentinel_map
    ov.insert(np.full((2, 3), 500.0, np.float32))
    assert ov._alive_cache is sentinel_cache and ov._old2new is sentinel_map
    ov.delete(np.array([0]))
    assert ov._alive_cache is None and ov._old2new is None


def test_serve_config_rejects_k_zero():
    with pytest.raises(ValueError, match="serving k"):
        ServeConfig(k=0)
    assert ServeConfig(k=None).k is None   # None still means "prepared k"


def test_mutation_fuzz_duplicate_flavor_hits_base_points():
    """The tie-hazard insert flavor must produce exact copies of an
    initial-cloud point (bit-identical f32 coords), across campaign
    seeds."""
    from cuda_knearests_tpu.fuzz.mutation import (MutationSpec,
                                                  generate_ops,
                                                  initial_points)

    found = False
    for seed in range(40):
        spec = MutationSpec(seed=seed, n0=50, n_ops=12, k=4)
        pts0 = initial_points(spec)
        for op in generate_ops(spec):
            if op["op"] != "insert":
                continue
            pts = op["points"]
            if pts.shape[0] and (pts == pts[0]).all() and \
                    (pts0 == pts[0]).all(axis=1).any():
                found = True
                break
        if found:
            break
    assert found, "no seed in 0..39 produced a base-point duplicate insert"


# -- ISSUE 8 satellite: ExecutableCache LRU eviction x memoized FoF -----------

def test_exec_cache_eviction_mid_session_fof_recompiles():
    """Eviction pressure mid-session must never corrupt the daemon's
    memoized FoF answer: the memo is daemon-owned host state, so an LRU
    eviction of the FoF executables (capacity pressure from query-bucket
    launches) costs exactly one recompile on the next cache MISS -- the
    post-mutation FoF must rebuild its executables and still match a fresh
    rebuild-from-scratch solve, not serve a stale or crashed reply."""
    from cuda_knearests_tpu.cluster.fof import fof_labels

    pts = generate_uniform(3_000, seed=3)
    p = KnnProblem.prepare(pts, KnnConfig(k=8, adaptive=False))
    daemon = ServeDaemon(p, ServeConfig(max_batch=32, max_delay_s=100.0,
                                        warmup=False))
    cache = dispatch.EXEC_CACHE
    cache.clear()
    old_cap = cache.maxsize
    try:
        cache.maxsize = 3  # tiny cap: three query buckets evict everything
        [r1] = daemon.submit(1, "fof", 25.0)
        assert r1.ok, r1.error
        labels0 = np.asarray(r1.labels)
        # between mutations, repeated FoF answers from the memo
        [r2] = daemon.submit(2, "fof", 25.0)
        assert r2.ok and daemon.fof_memo_hits == 1
        np.testing.assert_array_equal(np.asarray(r2.labels), labels0)
        # three differently-bucketed query batches thrash the tiny cache:
        # the FoF executables are now evicted
        for i, m in enumerate((1, 9, 17)):
            daemon.submit(10 + i, "query",
                          np.full((m, 3), 500.0, np.float32))
            daemon.drain()
        assert cache.evictions > 0
        assert daemon.stats_dict()["exec_cache_evictions"] > 0
        # a mutation invalidates the memo; the next FoF must RECOMPILE
        # (fresh cache misses) and still answer exactly
        [mr] = daemon.submit(50, "insert",
                             np.full((4, 3), 321.5, np.float32))
        assert mr.ok, mr.error
        misses_before = cache.misses
        [r3] = daemon.submit(51, "fof", 25.0)
        assert r3.ok, r3.error
        assert cache.misses > misses_before  # rebuilt, not stale
        ref = fof_labels(daemon.overlay.mutated_points(), 25.0)
        np.testing.assert_array_equal(np.asarray(r3.labels), ref.labels)
        assert r3.n_clusters == ref.n_clusters
    finally:
        cache.maxsize = old_cap
        cache.clear()
