"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip sharding is tested without hardware via XLA host-device emulation
(``--xla_force_host_platform_device_count``) -- a capability the reference lacks
entirely (its only test binary requires a physical GPU, SURVEY.md section 4).
The flags must be set before jax initializes, hence here.
"""

import os

# Hard assignment, not setdefault: the launcher environment may export
# JAX_PLATFORMS=axon (hardware pin), and the package honors the env var at
# import -- tests must run on the emulated CPU mesh no matter what.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

from cuda_knearests_tpu.utils.platform import enable_compile_cache  # noqa: E402

# Persist XLA compiles across pytest runs (keyed by jax on backend/options,
# so the emulated-mesh CPU programs never collide with hardware entries).
enable_compile_cache()

# The environment's sitecustomize may pre-register a hardware TPU backend and
# widen jax_platforms behind our back; tests must run on the emulated CPU mesh
# regardless (and not hang if the hardware tunnel is down), so force the
# platform again at config level -- this wins because it runs after any
# site-level registration but before first backend use.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(scope="session")
def uniform_10k():
    from cuda_knearests_tpu.io import generate_uniform
    return generate_uniform(10_000, seed=42)


@pytest.fixture(scope="session")
def blue_8k():
    from cuda_knearests_tpu.io import generate_blue_noise
    return generate_blue_noise(8_000, seed=17)


@pytest.fixture(scope="session")
def pts20k():
    """The reference's one shipped fixture, normalized (pts20K.xyz, 20,626 pts)."""
    from cuda_knearests_tpu.io import get_dataset
    return get_dataset("pts20K.xyz")


def brute_knn_np(points: np.ndarray, queries_idx: np.ndarray, k: int) -> np.ndarray:
    """Reference-free numpy brute force (self excluded by index): (m, k) ids."""
    out = np.empty((len(queries_idx), k), np.int64)
    for row, qi in enumerate(queries_idx):
        d2 = ((points[qi] - points) ** 2).sum(-1)
        d2[qi] = np.inf
        out[row] = np.argsort(d2, kind="stable")[:k]
    return out
