"""Multi-chip slab-sharding tests on the emulated 8-device CPU mesh.

The capability under test has no reference counterpart (the reference is
single-GPU); correctness bar per BASELINE.json: sharded results must agree with
the single-chip engine / exact brute force."""

import numpy as np
import pytest

from cuda_knearests_tpu import KnnConfig, KnnProblem
from cuda_knearests_tpu.parallel.sharded import ShardedKnnProblem, _slab_bounds
from conftest import brute_knn_np


def test_slab_bounds_cover_grid():
    for dim, s, ndev in [(21, 4, 8), (16, 4, 4), (9, 4, 8), (32, 8, 2)]:
        zc0, zc1, zcap = _slab_bounds(dim, s, ndev)
        assert zcap % s == 0
        # slabs tile [0, dim) without overlap
        cover = []
        for a, b in zip(zc0, zc1):
            cover.extend(range(a, min(b, dim)))
        assert cover == list(range(dim))


def test_halo_too_deep_raises(uniform_10k):
    # dim ~ 15 -> 8 devices -> 3-cell slabs; an explicit 30-cell ring radius
    # cannot be haloed from adjacent chips
    with pytest.raises(ValueError, match="halo"):
        ShardedKnnProblem.prepare(uniform_10k, n_devices=8,
                                  config=KnnConfig(k=10, ring_radius=30))


@pytest.mark.parametrize("ndev", [1, 8])
def test_sharded_matches_single_chip(uniform_10k, ndev):
    cfg = KnnConfig(k=10)
    sp = ShardedKnnProblem.prepare(uniform_10k, n_devices=ndev, config=cfg)
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    p = KnnProblem.prepare(uniform_10k, cfg)
    p.solve()
    ref = p.get_knearests_original()
    for i in range(0, len(uniform_10k), 97):
        assert set(ref[i].tolist()) == set(nbrs[i].tolist()), f"point {i}"


def test_sharded_exact_vs_brute(blue_8k, rng):
    sp = ShardedKnnProblem.prepare(blue_8k, n_devices=8, config=KnnConfig(k=10))
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    q = rng.integers(0, len(blue_8k), 48)
    ref = brute_knn_np(blue_8k, q, 10)
    for row, qi in enumerate(q):
        assert set(ref[row].tolist()) == set(nbrs[qi].tolist())
    assert (np.diff(d2, axis=1) >= 0).all()


def test_sharded_boundary_queries_certified(uniform_10k):
    """Queries in slab-face cells are the ones that need the halo; with halo
    depth == ring radius they must certify at the same rate as the interior
    (here: all of them)."""
    cfg = KnnConfig(k=10)
    sp = ShardedKnnProblem.prepare(uniform_10k, n_devices=4, config=cfg)
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    # and every point got a full neighbor list
    assert (nbrs >= 0).all()


def test_sharded_pallas_matches_xla(blue_8k):
    """The in-shard_map Pallas kernel (interpret mode here) must match the
    chunked XLA scan bit-for-bit, including halo-crossing neighbors."""
    cfg_x = KnnConfig(k=8, sc_batch=16, backend="xla")
    cfg_p = KnnConfig(k=8, sc_batch=16, backend="pallas", interpret=True)
    nx, dx, cx = ShardedKnnProblem.prepare(blue_8k, n_devices=2,
                                           config=cfg_x).solve()
    np_, dp, cp = ShardedKnnProblem.prepare(blue_8k, n_devices=2,
                                            config=cfg_p).solve()
    np.testing.assert_array_equal(nx, np_)
    np.testing.assert_array_equal(dx, dp)
    assert cx.all() and cp.all()


def test_distributed_helpers_and_custom_mesh(blue_8k):
    from cuda_knearests_tpu.parallel import init_distributed, z_mesh

    init_distributed()  # single-process: must be a safe no-op
    mesh = z_mesh()
    assert mesh.devices.size == 8 and mesh.axis_names == ("z",)
    sp = ShardedKnnProblem.prepare(blue_8k, mesh=mesh, config=KnnConfig(k=10))
    nbrs, d2, cert = sp.solve()
    assert cert.all() and (nbrs >= 0).all()


def test_sharded_clustered_points():
    """Heavily clustered data (most points in few cells) stays exact --
    capacities are measured maxima, not averages."""
    rng = np.random.default_rng(5)
    cluster = 450.0 + 40.0 * rng.standard_normal((3600, 3))
    spread = rng.random((400, 3)) * 1000.0
    pts = np.clip(np.concatenate([cluster, spread]), 0.0, 1000.0
                  ).astype(np.float32)
    sp = ShardedKnnProblem.prepare(pts, n_devices=4, config=KnnConfig(k=5))
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    q = np.random.default_rng(0).integers(0, len(pts), 24)
    ref = brute_knn_np(pts, q, 5)
    for row, qi in enumerate(q):
        assert set(ref[row].tolist()) == set(nbrs[qi].tolist())


def test_per_chip_capacity_classes():
    """VERDICT round-2 item 6: a dense blob on one chip must size only that
    chip's tiles -- other chips keep capacities from their own local density,
    and no chip inherits the blob's ccap."""
    rng = np.random.default_rng(11)
    bg = rng.random((8000, 3)).astype(np.float32) * 1000.0
    blob = (np.float32([500, 500, 60])
            + 8.0 * rng.standard_normal((4000, 3)).astype(np.float32))
    pts = np.clip(np.concatenate([bg, blob]), 0.0, 1000.0).astype(np.float32)
    sp = ShardedKnnProblem.prepare(pts, n_devices=4, config=KnnConfig(k=10))
    # blob z ~ 60/1000 -> chip 0; far chips see only background density
    ccap = [max((c.ccap for c in p.classes), default=0) for p in sp.chip_plans]
    qcap = [max((c.qcap for c in p.classes), default=0) for p in sp.chip_plans]
    assert ccap[0] > 2 * max(ccap[2], ccap[3]), (
        f"blob chip ccap {ccap[0]} should dwarf far-chip ccaps {ccap}")
    assert qcap[0] > 2 * max(qcap[2], qcap[3]), qcap
    # and the solve stays exact
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    q = rng.integers(0, len(pts), 16)
    ref = brute_knn_np(pts, q, 10)
    for row, qi in enumerate(q):
        assert set(ref[row].tolist()) == set(nbrs[qi].tolist())


@pytest.mark.slow
def test_per_device_footprint_scales(rng):
    """VERDICT round-2 item 5: at 1M+ points over 8 devices, no device holds
    the global array -- per-chip capacities (and thus per-device bytes) scale
    ~1/ndev, and prepare never materializes a global device-resident sort."""
    from cuda_knearests_tpu.io import generate_uniform

    n, ndev = 1_000_000, 8
    pts = generate_uniform(n, seed=4)
    sp = ShardedKnnProblem.prepare(pts, n_devices=ndev, config=KnnConfig(k=10))
    meta = sp.meta
    # slab population cap is ~n/ndev (uniform data): generous 1.35x slack
    assert meta.pcap <= 1.35 * n / ndev, (meta.pcap, n / ndev)
    # halo blocks are a small fraction of a slab
    assert meta.hcap < meta.pcap
    # per-device resident build state: points + ids + CSR + halo blocks
    per_dev_bytes = (meta.pcap * (12 + 4)                 # spts + sids
                     + meta.zcap * meta.dim ** 2 * 4      # counts
                     + 2 * meta.hcap * (12 + 4)           # halo pts + ids
                     + 2 * meta.radius * meta.dim ** 2 * 4)
    global_bytes = n * 16
    assert per_dev_bytes < 0.3 * global_bytes, (per_dev_bytes, global_bytes)
    # every sharded build output splits its leading axis across the mesh
    for name, arr in sp.dev.items():
        assert arr.shape[0] == ndev, name
        shard = arr.addressable_shards[0].data
        assert shard.shape[0] == 1, name


def test_sharded_query_matches_brute(blue_8k, rng):
    """External queries against a sharded problem: routed by owning slab,
    exact vs numpy brute force (incl. queries near slab boundaries)."""
    from cuda_knearests_tpu.io import generate_uniform

    sp = ShardedKnnProblem.prepare(blue_8k, n_devices=4, config=KnnConfig(k=10))
    queries = generate_uniform(300, seed=41)
    nbrs, d2 = sp.query(queries, k=10)
    assert nbrs.shape == (300, 10)
    for i in rng.integers(0, 300, 20):
        dd = ((queries[i] - blue_8k) ** 2).sum(-1)
        assert set(np.argsort(dd, kind="stable")[:10]) == set(nbrs[i].tolist()), i
    assert (np.diff(d2, axis=1) >= 0).all()
    with pytest.raises(ValueError, match="exceeds the prepared k"):
        sp.query(queries, k=11)


def test_sharded_stats(uniform_10k):
    sp = ShardedKnnProblem.prepare(uniform_10k, n_devices=4,
                                   config=KnnConfig(k=10))
    s = sp.print_stats()
    assert s["n_devices"] == 4 and s["n_points"] == len(uniform_10k)
    assert len(s["chips"]) == 4
    assert sum(c["n_points"] for c in s["chips"]) == len(uniform_10k)
    for c in s["chips"]:
        for cl in c["classes"]:
            assert cl["route"] in ("pallas", "dense", "streamed")
            assert cl["qcap"] >= 1 and cl["ccap"] >= 10


def test_sharded_degenerate_inputs():
    """Tiny/degenerate point sets through the full mesh path: n < k, a
    single point, identical points, and an all-one-slab distribution (7 of 8
    chips empty) must all survive and stay exact."""
    rng = np.random.default_rng(3)
    # n < k and n < ndev
    tiny = (rng.random((5, 3)) * 1000).astype(np.float32)
    nbrs, _, cert = ShardedKnnProblem.prepare(
        tiny, n_devices=8, config=KnnConfig(k=10)).solve()
    assert nbrs.shape == (5, 10) and cert.all()
    assert (nbrs[:, :4] >= 0).all() and (nbrs[:, 4:] == -1).all()
    # single point
    one = np.float32([[500.0, 500.0, 500.0]])
    nbrs, _, cert = ShardedKnnProblem.prepare(
        one, n_devices=4, config=KnnConfig(k=3)).solve()
    assert (nbrs == -1).all() and cert.all()
    # identical points: k neighbors each, none itself
    same = np.full((30, 3), 777.0, np.float32)
    nbrs, d2, cert = ShardedKnnProblem.prepare(
        same, n_devices=4, config=KnnConfig(k=4)).solve()
    assert cert.all() and (d2 == 0.0).all()
    for r in range(30):
        assert r not in nbrs[r].tolist()
        assert len(set(nbrs[r].tolist())) == 4
    # everything in one thin z-slab: most chips own nothing
    slab = (rng.random((4000, 3)) * np.float32([1000, 1000, 40])).astype(
        np.float32)
    nbrs, _, cert = ShardedKnnProblem.prepare(
        slab, n_devices=8, config=KnnConfig(k=5)).solve()
    assert cert.all() and (nbrs >= 0).all()
    q = rng.integers(0, 4000, 10)
    ref = brute_knn_np(slab, q, 5)
    for row, qi in enumerate(q):
        assert set(ref[row].tolist()) == set(nbrs[qi].tolist())


@pytest.mark.slow
def test_sharded_1m_exact_sampled():
    """Scale exactness: 1M uniform points over 8 emulated devices, sampled
    differential against the C++ oracle (the sharded_10m_k10 config's shape,
    scaled to what an emulated CPU mesh can solve in minutes)."""
    from cuda_knearests_tpu.io import generate_uniform
    from cuda_knearests_tpu.oracle import KdTreeOracle, native_available

    if not native_available():
        pytest.skip("numpy-brute oracle fallback would need ~6 GiB at 1M")
    n = 1_000_000
    pts = generate_uniform(n, seed=4)
    sp = ShardedKnnProblem.prepare(pts, n_devices=8, config=KnnConfig(k=10))
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    rng = np.random.default_rng(9)
    sample = np.sort(rng.choice(n, 3000, replace=False).astype(np.int32))
    oracle = KdTreeOracle(pts)
    ref_ids, ref_d2 = oracle.knn(pts[sample], 10, exclude_ids=sample)
    for row, qi in enumerate(sample):
        if set(nbrs[qi].tolist()) == set(ref_ids[row].tolist()):
            continue
        # a disagreeing row is acceptable ONLY as an exact f32 tie: the
        # engine's sorted distances must equal the oracle's
        dd = ((pts[qi].astype(np.float64)
               - pts[nbrs[qi]].astype(np.float64)) ** 2).sum(-1)
        np.testing.assert_allclose(np.sort(dd), ref_d2[row].astype(np.float64),
                                   rtol=1e-6, err_msg=f"query {qi}")


def test_dryrun_multichip_entry():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


# -- slow-profile coverage restorations --------------------------------------
# The default profile unified k/ndev across tests so compile caches are
# shared (suite-time budget, VERDICT round-2 item 7); the dropped
# configurations stay on record here and run with `pytest -m slow` / `-m ""`.

@pytest.mark.slow
def test_sharded_matches_single_chip_middle_mesh(uniform_10k):
    """ndev=2: the two-slab halo topology (each chip has exactly one
    neighbor) dropped from the default parametrization."""
    cfg = KnnConfig(k=10)
    sp = ShardedKnnProblem.prepare(uniform_10k, n_devices=2, config=cfg)
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    p = KnnProblem.prepare(uniform_10k, cfg)
    p.solve()
    ref = p.get_knearests_original()
    for i in range(0, len(uniform_10k), 97):
        assert set(ref[i].tolist()) == set(nbrs[i].tolist()), f"point {i}"


@pytest.mark.slow
def test_sharded_pallas_matches_xla_full_mesh(blue_8k):
    """8-device variant of the kernel-vs-XLA bit-for-bit equivalence (the
    default profile runs it at 2 devices)."""
    cfg_x = KnnConfig(k=8, sc_batch=16, backend="xla")
    cfg_p = KnnConfig(k=8, sc_batch=16, backend="pallas", interpret=True)
    nx, dx, cx = ShardedKnnProblem.prepare(blue_8k, n_devices=8,
                                           config=cfg_x).solve()
    np_, dp, cp = ShardedKnnProblem.prepare(blue_8k, n_devices=8,
                                            config=cfg_p).solve()
    np.testing.assert_array_equal(nx, np_)
    np.testing.assert_array_equal(dx, dp)
    assert cx.all() and cp.all()


@pytest.mark.slow
def test_sharded_exact_vs_brute_large_k(blue_8k, rng):
    """k=15 (> the unified default 10) against numpy brute force."""
    sp = ShardedKnnProblem.prepare(blue_8k, n_devices=8,
                                   config=KnnConfig(k=15))
    nbrs, d2, cert = sp.solve()
    assert cert.all()
    q = rng.integers(0, len(blue_8k), 48)
    ref = brute_knn_np(blue_8k, q, 15)
    for row, qi in enumerate(q):
        assert set(ref[row].tolist()) == set(nbrs[qi].tolist())


def test_query_on_empty_slab_chip():
    """A query whose owner chip has an empty class schedule (no points in
    that slab) must resolve exactly via the oracle, not crash."""
    rng = np.random.default_rng(21)
    pts = rng.random((4000, 3)).astype(np.float32) * [1000.0, 1000.0, 180.0]
    pts = np.clip(pts, 0.0, 1000.0).astype(np.float32)
    sp = ShardedKnnProblem.prepare(pts, n_devices=4, config=KnnConfig(k=10))
    q = np.float32([[500.0, 500.0, 900.0], [10.0, 10.0, 50.0]])
    ids, d2 = sp.query(q, k=10)
    for j in range(2):
        dd = ((q[j] - pts) ** 2).sum(-1)
        assert set(ids[j].tolist()) == set(
            np.argsort(dd, kind="stable")[:10].tolist()), j


def test_sharded_query_radius_matches_numpy(blue_8k, rng):
    """query_radius on a 4-dev mesh mirrors the single-chip contract
    (test_query.py::test_query_radius_matches_numpy): exact in-range sets,
    truncation flagged at the cap, rows ascending (VERDICT r3 next #7)."""
    from cuda_knearests_tpu.io import generate_uniform

    sp = ShardedKnnProblem.prepare(blue_8k, n_devices=4,
                                   config=KnnConfig(k=10))
    queries = generate_uniform(120, seed=55)
    radius = 45.0
    ids, d2, counts, truncated = sp.query_radius(queries, radius,
                                                 max_neighbors=10)
    for i in rng.integers(0, 120, 15):
        dd = ((queries[i] - blue_8k) ** 2).sum(-1)
        ref = set(np.nonzero(dd <= radius * radius)[0].tolist())
        got = set(ids[i][ids[i] >= 0].tolist())
        if truncated[i]:
            assert got <= ref and len(got) == 10
        else:
            assert got == ref, i
            assert counts[i] == len(ref)
    d2c = np.where(np.isfinite(d2), d2, np.float32(3.0e38))
    assert (np.diff(d2c, axis=1) >= 0).all()


def test_sharded_query_radius_cap_flag(blue_8k):
    sp = ShardedKnnProblem.prepare(blue_8k, n_devices=4,
                                   config=KnnConfig(k=5))
    qs = blue_8k[:16]
    ids, d2, counts, truncated = sp.query_radius(qs, 1500.0, max_neighbors=5)
    assert truncated.all() and (counts == 5).all()
    with pytest.raises(ValueError, match="exceeds the prepared k"):
        sp.query_radius(qs, 10.0, max_neighbors=99)


def test_sharded_get_edges_matches_single_chip(uniform_10k):
    """The sharded kNN graph equals the single-chip one on the same data
    (both exact, original indexing; VERDICT r3 next #7)."""
    cfg = KnnConfig(k=6)
    sp = ShardedKnnProblem.prepare(uniform_10k, n_devices=4, config=cfg)
    solved = sp.solve()
    e_sh = sp.get_edges(symmetric=True, solved=solved)

    p = KnnProblem.prepare(uniform_10k, cfg)
    p.solve()
    e_single = p.get_edges(symmetric=True)
    # symmetric + deduplicated edge sets are canonical up to exact-distance
    # ties; uniform_10k is float32 random -> tie-free in practice
    assert e_sh.shape == e_single.shape
    assert np.array_equal(e_sh, e_single)
    # directed form: every row's out-degree is k
    e_dir = sp.get_edges(solved=solved)
    assert e_dir.shape == (len(uniform_10k) * 6, 2)


def test_sharded_drop_ready_releases_and_rebuilds(blue_8k):
    """drop_ready() empties the per-chip prepack cache; the next solve
    rebuilds it and still answers exactly (ADVICE r3: cache-eviction hook
    for memory-tight workloads)."""
    sp = ShardedKnnProblem.prepare(blue_8k, n_devices=4,
                                   config=KnnConfig(k=8))
    n1, d1, c1 = sp.solve()
    assert len(sp._ready_cache) > 0
    sp.drop_ready()
    assert len(sp._ready_cache) == 0
    n2, d2, c2 = sp.solve()
    assert np.array_equal(n1, n2) and np.array_equal(d1, d2)
    # single-chip eviction form
    some = next(iter(sp._ready_cache))
    sp.drop_ready(some)
    assert some not in sp._ready_cache


def test_sharded_blocked_kernel_matches_xla(blue_8k):
    """The blocked two-stage kernel rides the per-chip class schedule too:
    sharded results with kernel='blocked' (interpret) must match the XLA
    scan bit-for-bit, including halo-crossing neighbors."""
    cfg_x = KnnConfig(k=8, sc_batch=16, backend="xla")
    cfg_b = KnnConfig(k=8, sc_batch=16, backend="pallas", interpret=True,
                      kernel="blocked")
    nx, dx, cx = ShardedKnnProblem.prepare(blue_8k, n_devices=2,
                                           config=cfg_x).solve()
    nb, db, cb = ShardedKnnProblem.prepare(blue_8k, n_devices=2,
                                           config=cfg_b).solve()
    np.testing.assert_array_equal(nx, nb)
    np.testing.assert_array_equal(dx, db)
    assert cx.all() and cb.all()
