"""The flagship differential test: TPU engine vs exact C++ kd-tree oracle on the
reference's shipped fixture -- the re-expression of the reference's entire test
program (/root/reference/test_knearests.cu:117-235) as described in SURVEY.md
section 4: permutation sanity, duplicate check, and exact per-point neighbor-set
agreement with the oracle."""

import numpy as np
import pytest

from cuda_knearests_tpu import KnnConfig, KnnProblem
from cuda_knearests_tpu.oracle import KdTreeOracle


@pytest.fixture(scope="module")
def solved_20k(pts20k):
    problem = KnnProblem.prepare(pts20k, KnnConfig(k=10))
    problem.solve()
    return problem


def test_permutation_bijection(solved_20k):
    # reference: sort + adjacency assert (test_knearests.cu:162-168)
    perm = solved_20k.get_permutation()
    np.testing.assert_array_equal(np.sort(perm), np.arange(len(perm)))


def test_no_duplicate_neighbors(solved_20k):
    # reference: per-point std::set scan (test_knearests.cu:174-191)
    nbrs = solved_20k.get_knearests_original()
    n, k = nbrs.shape
    valid = nbrs >= 0
    assert valid.all()
    sorted_rows = np.sort(nbrs, axis=1)
    assert (np.diff(sorted_rows, axis=1) > 0).all()


def test_exact_match_vs_oracle(solved_20k, pts20k):
    """The core check (reference: test_knearests.cu:215-232): per-point sorted
    neighbor-id lists must agree elementwise with the exact oracle.

    One refinement over the reference: when the k-th and (k+1)-th candidate are
    *exactly* tied in f32 (it happens ~3 times in 20,626 points on this fixture),
    either id is a correct answer -- the reference's all-or-nothing assert is
    only valid on tie-free data (SURVEY.md section 7 "hard parts").  Ids may
    differ solely within such exact tie groups at the k-th distance.
    """
    nbrs = solved_20k.get_knearests_original()
    oracle = KdTreeOracle(pts20k)
    ref_ids, ref_d2 = oracle.knn_all_points(k=10)
    got = np.sort(nbrs, axis=1)
    ref = np.sort(ref_ids, axis=1)
    mismatch = np.nonzero((got != ref).any(axis=1))[0]
    hard_fail = []
    for i in mismatch:
        diff_ids = set(got[i].tolist()) ^ set(ref[i].tolist())
        kth = float(ref_d2[i, -1])
        d2 = ((pts20k[list(diff_ids)].astype(np.float64)
               - pts20k[i].astype(np.float64)) ** 2).sum(-1)
        # tie window: a few f32 ulps around the k-th distance -- XLA may fuse
        # (FMA) the distance arithmetic, legitimately flipping 1-ulp orderings
        if not np.allclose(d2, kth, rtol=2e-6, atol=0.0):
            hard_fail.append(int(i))
    if hard_fail:
        i = hard_fail[0]
        raise AssertionError(
            f"{len(hard_fail)} points disagree beyond exact ties; first at "
            f"point {i}: engine={got[i].tolist()} oracle={ref[i].tolist()} "
            f"oracle_d2={ref_d2[i].tolist()}")
    # ties must stay rare -- a real engine bug would blow this up
    assert mismatch.size <= 10


def test_distances_match_oracle(solved_20k, pts20k):
    """Same arithmetic on both sides ('diff' path) -> distances agree to float
    exactness, not just id sets."""
    d2 = solved_20k.get_dists_sq()
    perm = solved_20k.get_permutation()
    d2_orig = np.empty_like(d2)
    d2_orig[perm] = d2
    oracle = KdTreeOracle(pts20k)
    _, ref_d2 = oracle.knn_all_points(k=10)
    np.testing.assert_allclose(d2_orig, ref_d2, rtol=1e-6, atol=1e-3)


def test_certified_complete(solved_20k):
    assert np.asarray(solved_20k.result.certified).all()
