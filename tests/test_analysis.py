"""Tier-1 gate for the kntpu-check analysis subsystem (ISSUE 3).

Three layers, mirroring the subsystem:

* the lint engine against a fixture corpus (every rule fires exactly where
  a known-bad snippet plants it, stays quiet on waived twins) and against
  the shipped tree (zero findings vs the committed baseline);
* the contract engine against the shipped tree (clean) and against every
  seeded fault (each detector demonstrably fires);
* the CLI's exit-code contract, including the acceptance criterion that
  ``python -m cuda_knearests_tpu.analysis`` exits non-zero on a seeded
  contract violation and a seeded lint hazard, zero on the shipped tree.

Also pins the satellite audits: margin_summary's f64 certificate math and
the sharded partition's i32 downcast.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


def _lint(path):
    from cuda_knearests_tpu.analysis.lint import lint_paths

    return lint_paths([os.path.join(FIXTURES, path)])


# -- lint engine: fixture corpus ----------------------------------------------

@pytest.mark.parametrize("fixture,rule,lines", [
    ("bad_tracer_leak.py", "tracer-leak", {11, 16}),
    ("bad_wide_dtype.py", "wide-dtype", {6, 7}),
    ("bad_host_sync_loop.py", "host-sync-loop", {8, 9, 10}),
    # the retired per-class readback loop (PR 5's one-sync solve deleted the
    # engine's three waivers; this pins the rule still catches the pattern)
    ("bad_per_class_readback.py", "host-sync-loop", {15, 16, 17}),
    ("bad_broad_except.py", "broad-except", {7}),
    ("bad_jnp_in_loop.py", "jnp-in-loop", {8}),
    ("bad_bare_valueerror.py", "bare-valueerror", {6, 8}),
    # ISSUE 13: bare time.time()/perf_counter() timing in serve/runtime
    # must route through obs.spans / stopwatch (the waived + monotonic
    # lines in the fixture must stay silent)
    ("bad_bare_timing.py", "bare-timing", {7, 9, 10}),
])
def test_rule_fires_exactly_where_planted(fixture, rule, lines):
    findings = _lint(fixture)
    assert {f.rule for f in findings} == {rule}, findings
    assert {f.line for f in findings} == lines, findings


def test_waivers_silence_every_rule():
    assert _lint("clean_waived.py") == []


def test_unreasoned_waiver_does_not_silence(tmp_path):
    """A marker without a `-- <why>` rationale is not a waiver: the reason
    IS the audit trail the markers exist to carry."""
    from cuda_knearests_tpu.analysis.lint import lint_paths

    bad = tmp_path / "unreasoned.py"
    bad.write_text(
        "import numpy as np\n"
        "x = np.float64(1.0)  # kntpu-ok: wide-dtype\n"
        "try:\n"
        "    pass\n"
        "except Exception:  # noqa: BLE001\n"
        "    pass\n")
    rules = {f.rule for f in lint_paths([str(bad)])}
    assert rules == {"wide-dtype", "broad-except"}


def test_duplicate_hazards_gate_by_count(tmp_path):
    """Line-free fingerprints collide for identical source lines; the
    occurrence index makes the baseline accept exactly the blessed COUNT,
    so one more identical hazard still fires the gate."""
    from cuda_knearests_tpu.analysis.findings import (diff_vs_baseline,
                                                      save_baseline)
    from cuda_knearests_tpu.analysis.lint import lint_paths

    dup = "try:\n    pass\nexcept Exception:\n    pass\n"
    f = tmp_path / "dups.py"
    f.write_text(dup * 2)
    two = lint_paths([str(f)])
    assert len(two) == 2
    base = tmp_path / "b.json"
    save_baseline(two, str(base))
    from cuda_knearests_tpu.analysis.findings import load_baseline

    bl = load_baseline(str(base))
    assert len(bl["fingerprints"]) == 2  # both occurrences, distinct
    new, _ = diff_vs_baseline(two, bl)
    assert new == []
    f.write_text(dup * 3)  # one MORE identical hazard
    new, _ = diff_vs_baseline(lint_paths([str(f)]), bl)
    assert len(new) == 1


def test_findings_are_typed_records():
    f = _lint("bad_broad_except.py")[0]
    assert f.rule == "broad-except" and f.severity == "error"
    assert f.path.endswith("bad_broad_except.py") and f.line == 7
    assert f.hint and f.fingerprint.startswith("broad-except:")
    # fingerprints are line-free: an edit above the site must not churn them
    assert ":7" not in f.fingerprint.rsplit(":", 1)[-1]


def test_rule_registry_is_pluggable_and_complete():
    from cuda_knearests_tpu.analysis.rules import all_rules

    ids = {r.rule_id for r in all_rules()}
    assert {"tracer-leak", "wide-dtype", "host-sync-loop", "broad-except",
            "jnp-in-loop", "bare-valueerror"} <= ids


# -- lint engine: the shipped tree is clean -----------------------------------

def test_lint_clean_on_shipped_tree():
    from cuda_knearests_tpu.analysis import diff_vs_baseline, run_lint

    new, _stale = diff_vs_baseline(run_lint())
    assert new == [], "\n".join(f.render() for f in new)


# -- contract engine ----------------------------------------------------------

def test_contracts_clean_on_shipped_tree():
    from cuda_knearests_tpu.analysis import run_contracts

    bad = [f for f in run_contracts() if f.severity == "error"]
    assert bad == [], "\n".join(f.render() for f in bad)


def test_contracts_report_waiver_and_census():
    from cuda_knearests_tpu.analysis import run_contracts

    info = [f for f in run_contracts() if f.severity == "info"]
    # the k-sublane waiver must actually exercise (k=50 configs) and the
    # recompile census must report -- silence would mean dead checks
    assert any(f.rule == "vmem-tile" and "waived" in f.message for f in info)
    assert any(f.rule == "recompile-key" for f in info)


@pytest.mark.parametrize("fault,rule", [
    ("scatter-map", "route-shape"),
    ("hbm-model", "hbm-model"),
    ("tile-misalign", "vmem-tile"),
])
def test_seeded_fault_is_detected(fault, rule):
    from cuda_knearests_tpu.analysis import run_contracts

    bad = [f for f in run_contracts(fault=fault) if f.severity == "error"]
    assert any(f.rule == rule for f in bad), bad


def test_unknown_fault_refused():
    from cuda_knearests_tpu.analysis import run_contracts

    with pytest.raises(ValueError, match="unknown analysis fault"):
        run_contracts(fault="nonsense")


# -- CLI: the acceptance-criterion exit codes ---------------------------------

def _cli(*args, env=None):
    e = dict(os.environ, JAX_PLATFORMS="cpu")
    e.update(env or {})
    return subprocess.run(
        [sys.executable, "-m", "cuda_knearests_tpu.analysis", *args],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=e)


def test_cli_zero_on_shipped_tree():
    r = _cli()
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new finding(s)" in r.stdout


def test_cli_nonzero_on_seeded_contract_violation():
    r = _cli("--engine", "contracts",
             env={"KNTPU_ANALYSIS_FAULT": "scatter-map"})
    assert r.returncode == 1, r.stdout + r.stderr
    assert "route-shape" in r.stdout


def test_cli_nonzero_on_seeded_lint_hazard(tmp_path):
    bad = tmp_path / "hazard.py"
    bad.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    r = _cli("--paths", str(bad))
    assert r.returncode == 2, r.stdout + r.stderr
    assert "broad-except" in r.stdout


def test_cli_json_mode(tmp_path):
    import json

    bad = tmp_path / "hazard.py"
    bad.write_text("import numpy as np\nx = np.float64(1.0)\n")
    r = _cli("--paths", str(bad), "--json")
    assert r.returncode == 2
    doc = json.loads(r.stdout)
    assert doc["ok"] is False and doc["analysis_version"]
    assert doc["findings"][0]["rule"] == "wide-dtype"


def test_cli_write_baseline_roundtrip(tmp_path):
    bad = tmp_path / "hazard.py"
    bad.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n")
    base = tmp_path / "baseline.json"
    r = _cli("--paths", str(bad), "--baseline", str(base),
             "--write-baseline")
    assert r.returncode == 0, r.stdout + r.stderr
    # the blessed finding no longer gates...
    r = _cli("--paths", str(bad), "--baseline", str(base))
    assert r.returncode == 0, r.stdout + r.stderr
    # ...but a fresh hazard still does (zero-vs-baseline, not zero-checks)
    bad.write_text("try:\n    x = 1\nexcept Exception:\n    pass\n"
                   "import numpy as np\ny = np.int64(2)\n")
    r = _cli("--paths", str(bad), "--baseline", str(base))
    assert r.returncode == 2, r.stdout + r.stderr


# -- traceability stamp (bench artifact wiring) -------------------------------

def test_analysis_stamp_fields():
    from cuda_knearests_tpu.analysis import ANALYSIS_VERSION, analysis_stamp

    stamp = analysis_stamp()
    assert stamp["analysis_version"] == ANALYSIS_VERSION
    assert len(stamp["analysis_baseline"]) == 12


def test_analysis_stamp_does_not_mutate_environment(monkeypatch):
    """The stamp is called by bench.py parents whose environment supervised
    workers inherit verbatim: if stamping pinned JAX_PLATFORMS=cpu, every
    TPU bench row would silently run on CPU with rc 0."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    from cuda_knearests_tpu.analysis import analysis_stamp

    analysis_stamp()
    assert "JAX_PLATFORMS" not in os.environ


def test_cli_refuses_contracts_with_paths(tmp_path):
    f = tmp_path / "x.py"
    f.write_text("x = 1\n")
    r = _cli("--engine", "contracts", "--paths", str(f))
    # argparse usage error, NOT a silent zero-checks 'clean' pass
    assert r.returncode == 2 and "cannot be combined" in r.stderr


def test_cli_refuses_unseedable_fault(tmp_path):
    """--fault with an invocation that skips the contract engine would be a
    self-test that seeds nothing and reports clean."""
    f = tmp_path / "x.py"
    f.write_text("x = 1\n")
    r = _cli("--paths", str(f), "--fault", "hbm-model")
    assert r.returncode == 2 and "does not run" in r.stderr
    # env-var form warns instead (external wrappers may export it broadly)
    r = _cli("--engine", "lint", env={"KNTPU_ANALYSIS_FAULT": "hbm-model"})
    assert "no fault was seeded" in r.stderr


def test_cli_pins_cpu_over_inherited_accelerator_pin():
    """An inherited JAX_PLATFORMS=tpu export must not make the gate try to
    acquire a chip (or fail as if the tree were at fault): the CLI
    overwrites the pin in its own process."""
    r = _cli("--engine", "lint", env={"JAX_PLATFORMS": "cpu,tpu"})
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_rows_carry_analysis_stamp():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    fields = bench._env_fields("cpu")
    assert "analysis_version" in fields and "analysis_baseline" in fields


# -- satellite audits ---------------------------------------------------------

def test_margin_summary_f64_certificate_math():
    """Pins the intentional f64 in utils/stats.py:64-65: the decertified
    boundary (ratio >= 1) must be decided at full host precision, and the
    documented edge cases must hold exactly."""
    from cuda_knearests_tpu.utils.stats import margin_summary

    kth = np.array([4.0, 9.0, 16.0, 16.0, 1.0], np.float32)
    msq = np.array([16.0, 16.0, 16.0, np.inf, 0.0], np.float32)
    out = margin_summary(kth, msq)
    assert out["n"] == 5
    # ratios: 0.5, 0.75, 1.0 (at bound), 0.0 (unconstrained), inf (0 margin)
    assert out["decertified"] == 2
    assert out["p50"] == pytest.approx(0.75)
    # a margin within one f32 ulp BELOW the kth distance must decertify:
    # f64 keeps the quotient > 1 where f32 arithmetic could collapse it to
    # exactly 1.0's neighborhood unpredictably
    kth1 = np.array([np.float32(1.0) + np.float32(1.2e-7)], np.float32)
    msq1 = np.array([1.0], np.float32)
    assert margin_summary(kth1, msq1)["decertified"] == 1
    assert margin_summary(msq1, kth1)["decertified"] == 0


def test_partition_host_i32_downcast_matches_i64_reference():
    """Pins the sharded.py audit downcast: chip bucketing computed in i32
    matches an independent i64 reference on the same points."""
    from cuda_knearests_tpu.parallel.sharded import _partition_host

    rng = np.random.default_rng(3)
    pts = (rng.random((2000, 3)) * 1000.0).astype(np.float32)
    dim, zcap, radius, ndev, domain = 9, 5, 2, 2, 1000.0
    _, bucket_ids, n_local, _, _ = _partition_host(
        pts, dim, zcap, radius, ndev, domain)
    cz = np.clip((pts[:, 2].astype(np.float64) * (dim / domain))
                 .astype(np.int64), 0, dim - 1)
    chip_ref = np.minimum(cz // zcap, ndev - 1)
    ref_counts = np.bincount(chip_ref, minlength=ndev)
    assert np.array_equal(n_local, ref_counts.astype(np.int32))
    for d in range(ndev):
        got = np.sort(bucket_ids[d][: n_local[d]])
        want = np.sort(np.nonzero(chip_ref == d)[0].astype(np.int32))
        assert np.array_equal(got, want)


def test_cli_refuses_empty_paths(tmp_path):
    r = _cli("--paths", str(tmp_path / "typo_dir"))
    assert r.returncode == 2 and "do not exist" in r.stderr
    empty = tmp_path / "no_py"
    empty.mkdir()
    r = _cli("--paths", str(empty))
    assert r.returncode == 2 and "matched no .py files" in r.stderr


def test_host_grid_twin_matches_build_grid():
    """The contract engine plans against _host_grid's numpy twin of
    gridhash.build_grid; drift between them would make the gate trace a
    fiction while staying green -- pin table-for-table equality."""
    import jax

    from cuda_knearests_tpu.analysis.contracts import _host_grid
    from cuda_knearests_tpu.config import DEFAULT_CELL_DENSITY
    from cuda_knearests_tpu.ops.gridhash import build_grid

    rng = np.random.default_rng(5)
    pts = (1.0 + rng.random((500, 3)) * 998.0).astype(np.float32)
    twin, counts = _host_grid(pts, DEFAULT_CELL_DENSITY)
    real = build_grid(pts)
    assert twin.dim == real.dim and twin.domain == real.domain
    for name in ("points", "permutation", "cell_starts", "cell_counts"):
        a = np.asarray(jax.device_get(getattr(twin, name)))
        b = np.asarray(jax.device_get(getattr(real, name)))
        assert np.array_equal(a, b), name
    assert np.array_equal(counts, np.asarray(
        jax.device_get(real.cell_counts)))


def test_query_fixture_twin_matches_bucket_queries():
    """Same parity pin for the external-query route's host bucketing twin
    (_query_fixture vs ops.query.bucket_queries)."""
    from cuda_knearests_tpu.analysis.contracts import (_legacy_fixture,
                                                       _points,
                                                       _query_fixture)
    from cuda_knearests_tpu.ops.query import bucket_queries

    _cfg, grid, plan, _pack = _legacy_fixture(_points(7), 8, 3)
    queries, sc_counts, starts, q2cap, inv_flat, inv_sc = _query_fixture(
        grid, plan, 3)
    order, r_counts, r_starts, r_q2cap, r_inv, r_sid = bucket_queries(
        queries, grid, 3, plan.n_chunks * plan.batch)
    assert q2cap == r_q2cap
    assert np.array_equal(sc_counts, r_counts)
    assert np.array_equal(starts, r_starts)
    assert np.array_equal(inv_flat, r_inv)
    assert np.array_equal(inv_sc, r_sid)


def test_adaptive_abstract_plan_matches_concrete_shapes():
    """The abstract=True prepare (what the contract engine traces against)
    must mirror the real prepare exactly: same classes, same caps, same
    routes, same pk/tgt shapes -- drift here would make the gate check a
    fiction."""
    import jax

    from cuda_knearests_tpu.analysis.contracts import _host_grid
    from cuda_knearests_tpu.config import KnnConfig
    from cuda_knearests_tpu.ops.adaptive import build_adaptive_plan

    rng = np.random.default_rng(11)
    pts = (1.0 + rng.random((300, 3)) * 998.0).astype(np.float32)
    cfg = KnnConfig(k=8, interpret=True)
    grid, counts = _host_grid(pts, cfg.density)
    real = build_adaptive_plan(grid, cfg, cell_counts_host=counts,
                               on_kernel_platform=True)
    abst = build_adaptive_plan(grid, cfg, cell_counts_host=counts,
                               on_kernel_platform=True, abstract=True)
    assert len(real.classes) == len(abst.classes)
    for rc, ac in zip(real.classes, abst.classes):
        assert (rc.route, rc.qcap, rc.qcap_pad, rc.ccap, rc.radius) == \
            (ac.route, ac.qcap, ac.qcap_pad, ac.ccap, ac.radius)
        r_leaves = jax.tree_util.tree_leaves((rc.pk, rc.tgt))
        a_leaves = jax.tree_util.tree_leaves((ac.pk, ac.tgt))
        assert [(l.shape, np.dtype(l.dtype)) for l in r_leaves] == \
            [(l.shape, np.dtype(l.dtype)) for l in a_leaves]
    assert real.inv_row.shape == abst.inv_row.shape


# -- ISSUE 8 satellites: --json schema + baseline schema versioning -----------

def test_cli_json_schema_stable(tmp_path):
    """The --json document is the CI annotation contract: stable top-level
    keys, a schema stamp, per-finding fingerprints, and severity counts."""
    import json

    bad = tmp_path / "hazard.py"
    bad.write_text("import numpy as np\nx = np.float64(1.0)\n")
    r = _cli("--paths", str(bad), "--json")
    assert r.returncode == 2
    doc = json.loads(r.stdout)
    assert set(doc) >= {"schema", "analysis_version", "analysis_baseline",
                        "analysis_equivalence", "engine", "findings",
                        "new", "stale_baseline", "counts", "ok"}
    assert doc["schema"] == 1 and doc["ok"] is False
    assert doc["counts"]["error"] + doc["counts"]["warning"] >= 1
    assert doc["counts"]["new"] >= 1
    f = doc["findings"][0]
    assert set(f) >= {"rule", "severity", "path", "line", "message",
                      "hint", "subject", "fingerprint"}
    assert f["fingerprint"] in doc["new"]


def test_stale_schema_baseline_refused(tmp_path):
    """A baseline written under an older fingerprint law must REFUSE (typed
    baseline-schema finding, rc 1), never silently gate against it."""
    import json

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    stale = tmp_path / "stale_baseline.json"
    stale.write_text(json.dumps(
        {"version": "1.0.0", "fingerprints": []}))  # v1: no schema field
    r = _cli("--paths", str(clean), "--baseline", str(stale))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "baseline-schema" in r.stdout
    # the same content WITH the current schema passes
    from cuda_knearests_tpu.analysis import BASELINE_SCHEMA

    fresh = tmp_path / "fresh_baseline.json"
    fresh.write_text(json.dumps(
        {"version": "2.0.0", "schema": BASELINE_SCHEMA,
         "fingerprints": []}))
    r = _cli("--paths", str(clean), "--baseline", str(fresh))
    assert r.returncode == 0, r.stdout + r.stderr


def test_committed_baseline_schema_current():
    from cuda_knearests_tpu.analysis import BASELINE_SCHEMA, load_baseline
    from cuda_knearests_tpu.analysis.findings import schema_finding

    base = load_baseline()
    assert base.get("schema") == BASELINE_SCHEMA
    assert base["fingerprints"] == []  # the empty-baseline policy holds
    assert schema_finding(base) is None
    assert schema_finding({"fingerprints": []}) is not None


def test_cli_verify_engine_wired():
    """--engine verify runs engine 3 alone: rc 0 on the shipped tree, rc 1
    under a seeded verifier fault (the acceptance exit-code contract)."""
    r = _cli("--engine", "verify")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "sync-budget" in r.stdout and "route-equiv" in r.stdout


def test_analysis_stamp_carries_equivalence_hash():
    from cuda_knearests_tpu.analysis import analysis_stamp

    stamp = analysis_stamp()
    assert len(stamp["analysis_equivalence"]) == 12
    assert stamp["analysis_equivalence"] != "none"


def test_cli_refuses_fault_engine_mismatch():
    """A verify fault with --engine contracts (or vice versa) would be
    silently ignored by the non-matching engine and report a false
    'tree clean' -- the CLI must refuse the mismatch outright."""
    r = _cli("--engine", "contracts", "--fault", "sync-leak")
    assert r.returncode == 2 and "does not run" in r.stderr
    r = _cli("--engine", "verify", "--fault", "scatter-map")
    assert r.returncode == 2 and "does not run" in r.stderr
    # env-var form warns (external wrappers may export it broadly)
    r = _cli("--engine", "contracts",
             env={"KNTPU_ANALYSIS_FAULT": "sync-leak"})
    assert "no fault was seeded" in r.stderr


def test_equivalence_trace_hashes_pin_epilogues():
    """The certificate's full-trace hashes are what license the matrix
    collapse: every route x epilogue family carries one, distinct between
    families (the scatter program is NOT the gather program)."""
    from cuda_knearests_tpu.analysis import equiv

    cert = equiv.load_certificates()
    for cell in cert["cells"]:
        g = cell["families"]["gather"]["trace_hashes"]
        s = cell["families"]["scatter"]["trace_hashes"]
        assert set(g) == set(s) == {"legacy-pack", "adaptive",
                                    "external-query", "sharded-chip"}
        for route in g:
            assert g[route] != s[route], (cell["k"], route)
